"""R2D2: recurrent-replay DQN (Kapturowski et al. 2019).

Reference analog: ``rllib/algorithms/r2d2/``. A GRU Q-network is trained
on replayed SEQUENCES instead of transitions: each stored sequence
carries the recurrent state at its start (the paper's "stored state"
strategy), the first ``burn_in`` steps warm the state without
contributing loss, and the remaining steps take double-Q TD updates
unrolled with ``lax.scan``. Sequences are chopped at episode boundaries
and padded with a validity mask.

Runs in-process (the feedforward EnvRunner protocol can't carry hidden
state); the bundled partially-observable env — CartPole with velocities
masked out (``CartPoleNoVel-v0``) — is unsolvable by a memoryless policy
beyond the random baseline, which is what the convergence test exploits.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import CartPole, EnvSpec, VectorEnv, register_env
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.tune.trainable import Trainable


class MaskedCartPole(VectorEnv):
    """CartPole with only the position components observable (cart x,
    pole angle) — velocity must be inferred from memory."""

    _KEEP = np.array([0, 2])  # x, theta

    def __init__(self, num_envs: int, seed: int = 0):
        self._inner = CartPole(num_envs, seed=seed)
        self.num_envs = num_envs
        self.spec = EnvSpec(obs_dim=2, num_actions=2)

    def reset(self) -> np.ndarray:
        return self._inner.reset()[:, self._KEEP]

    def step(self, actions):
        obs, r, d = self._inner.step(actions)
        return obs[:, self._KEEP], r, d


register_env("CartPoleNoVel-v0",
             lambda c: MaskedCartPole(c["num_envs"], seed=c.get("seed", 0)))


# ---------------------------------------------------------------- GRU ----

def init_gru(key, in_dim: int, hidden: int) -> Dict:
    kx, kh = jax.random.split(key)
    s_x = 1.0 / np.sqrt(in_dim)
    s_h = 1.0 / np.sqrt(hidden)
    return {
        "wx": jax.random.uniform(kx, (in_dim, 3 * hidden), minval=-s_x,
                                 maxval=s_x),
        "wh": jax.random.uniform(kh, (hidden, 3 * hidden), minval=-s_h,
                                 maxval=s_h),
        "b": jnp.zeros(3 * hidden),
    }


def gru_step(p: Dict, h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """One GRU step: h [B, H], x [B, E] -> h' [B, H]."""
    gx = x @ p["wx"] + p["b"]
    gh = h @ p["wh"]
    xr, xz, xn = jnp.split(gx, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1.0 - z) * n + z * h


class R2D2Config(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=R2D2, **kwargs)
        self.env = "CartPoleNoVel-v0"
        self.lr = 5e-4
        self.hidden = (64,)          # obs encoder widths
        self.gru_hidden = 128
        self.seq_len = 16            # stored sequence length
        self.burn_in = 4             # warm-up steps without loss
        self.buffer_size = 4_000     # in sequences
        self.learning_starts = 64    # sequences before training
        self.minibatch_size = 64     # sequences per update
        self.target_update_freq = 200
        self.updates_per_iter = 32
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 30_000


class R2D2(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return R2D2Config()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = R2D2Config().update_from_dict(config)
        cfg = self.config
        from ray_tpu.rl.env import make_env

        self.env = make_env(cfg.env, cfg.num_envs_per_runner,
                            cfg.env_config, seed=cfg.seed)
        spec = self.env.spec
        if not spec.discrete:
            raise ValueError("R2D2 requires discrete actions")
        self._A = spec.num_actions
        H = cfg.gru_hidden
        k_enc, k_gru, k_head = jax.random.split(jax.random.key(cfg.seed), 3)
        enc_dims = (spec.obs_dim,) + tuple(cfg.hidden)
        net = {
            "enc": models.init_mlp(k_enc, enc_dims, out_scale=1.0),
            "gru": init_gru(k_gru, enc_dims[-1], H),
            "head": models.init_mlp(k_head, (H, self._A)),
        }
        params = {"q": net,
                  "target": jax.tree_util.tree_map(jnp.array, net)}
        gamma, burn_in = cfg.gamma, cfg.burn_in

        def unroll(net_p, obs_seq, h0):
            """obs [B, L, D], h0 [B, H] -> q [B, L, A] via scan over L."""
            emb = jnp.tanh(models.mlp_forward(net_p["enc"], obs_seq))

            def step(h, x):
                h2 = gru_step(net_p["gru"], h, x)
                return h2, h2

            _, hs = jax.lax.scan(step, h0,
                                 jnp.swapaxes(emb, 0, 1))  # [L, B, H]
            hs = jnp.swapaxes(hs, 0, 1)                    # [B, L, H]
            return models.mlp_forward(net_p["head"], hs)

        def loss_fn(p, batch, key):
            del key
            q = unroll(p["q"], batch["obs"], batch["h0"])       # [B,L,A]
            q_tgt = unroll(p["target"], batch["obs"], batch["h0"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]                                # [B,L]
            # double-Q: next-step argmax from the online net, value from
            # the target net; shift left to align t -> t+1 (pad zeros)
            best_next = jnp.argmax(q, axis=-1)                  # [B,L]
            q_next = jnp.take_along_axis(
                q_tgt, best_next[..., None], axis=-1)[..., 0]
            q_next = jnp.concatenate(
                [q_next[:, 1:], jnp.zeros_like(q_next[:, :1])], axis=1)
            nonterminal = 1.0 - batch["dones"]
            target = batch["rewards"] + gamma * nonterminal \
                * jax.lax.stop_gradient(q_next)
            td = q_taken - target
            # loss only on trainable steps: valid, past the burn-in, and
            # either terminal (no bootstrap needed) or followed by a
            # valid step (bootstrap available)
            valid = batch["valid"]
            next_valid = jnp.concatenate(
                [valid[:, 1:], jnp.zeros_like(valid[:, :1])], axis=1)
            L = valid.shape[1]
            past_burn = (jnp.arange(L)[None, :] >= burn_in)
            mask = valid * jnp.maximum(batch["dones"], next_valid) \
                * past_burn
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            loss = jnp.sum(mask * td ** 2) / denom
            return loss, {"td_abs_mean": jnp.sum(mask * jnp.abs(td))
                          / denom,
                          "q_mean": jnp.sum(mask * q_taken) / denom}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def act_q(net_p, h, obs):
            emb = jnp.tanh(models.mlp_forward(net_p["enc"], obs))
            h2 = gru_step(net_p["gru"], h, emb)
            return h2, models.mlp_forward(net_p["head"], h2)

        self._act_q = act_q
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        N = self.env.num_envs
        self._obs = self.env.reset()
        self._h = np.zeros((N, H), dtype=np.float32)
        # per-env open sequence accumulators
        self._open: List[Dict[str, list]] = [self._new_seq(i)
                                             for i in range(N)]
        self._env_steps_total = 0
        self._grad_updates = 0
        self._ep_return = np.zeros(N)
        self._return_window: List[float] = []

    # -- sequence bookkeeping ---------------------------------------------

    def _new_seq(self, env_i: int) -> Dict[str, Any]:
        return {"h0": self._h[env_i].copy(), "obs": [], "actions": [],
                "rewards": [], "dones": []}

    def _flush_seq(self, env_i: int) -> None:
        cfg = self.config
        seq = self._open[env_i]
        t = len(seq["obs"])
        if t == 0:
            self._open[env_i] = self._new_seq(env_i)
            return
        L, D = cfg.seq_len, self.env.spec.obs_dim
        obs = np.zeros((L, D), dtype=np.float32)
        obs[:t] = np.stack(seq["obs"])
        acts = np.zeros(L, dtype=np.int64)
        acts[:t] = seq["actions"]
        rews = np.zeros(L, dtype=np.float32)
        rews[:t] = seq["rewards"]
        dones = np.zeros(L, dtype=np.float32)
        dones[:t] = seq["dones"]
        valid = np.zeros(L, dtype=np.float32)
        valid[:t] = 1.0
        self.buffer.add_batch({
            "obs": obs[None], "actions": acts[None], "rewards": rews[None],
            "dones": dones[None], "valid": valid[None],
            "h0": seq["h0"][None]})
        self._open[env_i] = self._new_seq(env_i)

    @property
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_total
                   / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial \
            + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def _collect(self, steps: int) -> None:
        cfg = self.config
        N = self.env.num_envs
        net = self.learner.get_params()["q"]
        for _ in range(steps):
            h2, q = self._act_q(net, jnp.asarray(self._h),
                                jnp.asarray(self._obs))
            # np.array (copy): device arrays surface as read-only views
            h2, q = np.array(h2), np.asarray(q)
            greedy = np.argmax(q, axis=-1)
            explore = self._rng.random(N) < self._epsilon
            rand = self._rng.integers(0, self._A, N)
            acts = np.where(explore, rand, greedy).astype(np.int64)
            next_obs, rewards, dones = self.env.step(acts)
            for i in range(N):
                seq = self._open[i]
                seq["obs"].append(self._obs[i])
                seq["actions"].append(acts[i])
                seq["rewards"].append(rewards[i])
                seq["dones"].append(float(dones[i]))
                if dones[i] or len(seq["obs"]) >= cfg.seq_len:
                    if dones[i]:
                        h2[i] = 0.0  # episode boundary resets the state
                    # update self._h BEFORE flushing: _flush_seq opens the
                    # successor via _new_seq, whose h0 copies self._h — it
                    # must see the post-step carried state
                    self._h[i] = h2[i]
                    self._flush_seq(i)
                else:
                    self._h[i] = h2[i]
            self._ep_return += rewards
            for i in np.nonzero(dones)[0]:
                self._return_window.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self._obs = next_obs
            self._env_steps_total += N
        self._return_window = self._return_window[-100:]

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {"epsilon": self._epsilon,
                                   "buffer_sequences": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            mlist = []
            for _ in range(cfg.updates_per_iter or 1):
                mb = self.buffer.sample(cfg.minibatch_size)
                mlist.append(self.learner.update_minibatch(mb))
                self._grad_updates += 1
                if self._grad_updates % cfg.target_update_freq == 0:
                    p = dict(self.learner.get_params())
                    p["target"] = jax.tree_util.tree_map(
                        jnp.array, p["q"])
                    self.learner.set_params(p)
            for k in mlist[0]:
                metrics[k] = float(np.mean([float(m[k]) for m in mlist]))
        metrics["env_steps_total"] = self._env_steps_total
        if self._return_window:
            metrics["episode_return_mean"] = float(
                np.mean(self._return_window))
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy episodes on a fresh env with a fresh recurrent state."""
        from ray_tpu.rl.env import make_env

        cfg = self.config
        env = make_env(cfg.env, cfg.num_envs_per_runner, cfg.env_config,
                       seed=cfg.seed + 991)
        N, H = env.num_envs, cfg.gru_hidden
        net = self.learner.get_params()["q"]
        obs = env.reset()
        h = np.zeros((N, H), dtype=np.float32)
        ep_ret = np.zeros(N)
        returns: List[float] = []
        for _ in range(4096):
            h2, q = self._act_q(net, jnp.asarray(h), jnp.asarray(obs))
            h, q = np.array(h2), np.asarray(q)
            obs, r, d = env.step(np.argmax(q, axis=-1).astype(np.int64))
            ep_ret += r
            for i in np.nonzero(d)[0]:
                returns.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
                h[i] = 0.0
            if len(returns) >= num_episodes:
                break
        return {"episodes": len(returns),
                "episode_return_mean": float(np.mean(returns))
                if returns else float("nan")}

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(
            np.asarray, self.learner.get_params()),
            "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.learner.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
