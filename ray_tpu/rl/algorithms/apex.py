"""Ape-X DQN: distributed prioritized experience replay.

Reference analog: ``rllib/algorithms/apex_dqn/apex_dqn.py`` (Horgan et
al. 2018). The three Ape-X signatures, on this framework's primitives:

1. **Async sampling fleet** — runners keep producing fragments under
   slightly stale params (the IMPALA inflight-refs pipeline, not the
   synchronous DQN gather), so the learner never waits on the slowest
   actor.
2. **Per-actor epsilon ladder** — runner ``i`` of ``N`` explores with
   ``eps_i = base ** (1 + 7 * i / (N - 1))`` (the paper's schedule): a
   few runners stay near-greedy while others explore hard, replacing the
   single annealed epsilon.
3. **Prioritized replay always on** — new fragments enter the buffer at
   max priority; sampled minibatches update priorities from the TD error
   (inherited from the DQN learner's ``td`` output).
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rl.algorithms.dqn import DQN
from ray_tpu.rl.config import AlgorithmConfig


class ApexDQNConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=ApexDQN, **kwargs)
        self.lr = 1e-3
        self.minibatch_size = 64
        self.num_env_runners = 4
        self.prioritized_replay = True
        self.apex_eps_base = 0.4
        self.apex_eps_alpha = 7.0
        self.updates_per_iter = 16


class ApexDQN(DQN):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ApexDQNConfig()

    def build_learner(self) -> None:
        cfg = self.config
        if not cfg.prioritized_replay:
            raise ValueError("ApexDQN requires prioritized_replay=True "
                             "(it IS the algorithm)")
        super().build_learner()
        self._inflight: Dict[Any, Any] = {}
        # epsilon ladder: runner i's exploration is fixed, not annealed
        n = max(1, len(self.runners))
        base, alpha = cfg.apex_eps_base, cfg.apex_eps_alpha
        self._runner_eps = [
            base ** (1 + alpha * i / max(1, n - 1)) for i in range(n)]

    def _params_for(self, runner_i: int):
        return self._runner_params(epsilon=self._runner_eps[runner_i])

    def _submit(self, runner_i: int) -> None:
        runner = self.runners[runner_i]
        ref = runner.sample.remote(self._params_for(runner_i))
        self._inflight[ref] = runner_i

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        submitted = set(self._inflight.values())
        for i in range(len(self.runners)):
            if i not in submitted:
                self._submit(i)
        # consume one round of fragments (whichever runners finish first)
        consumed = 0
        for _ in range(len(self.runners)):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
            ref = ready[0]
            runner_i = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            self._submit(runner_i)  # resubmit with fresh params
            self.buffer.add_batch(
                {k: batch[k] for k in
                 ("obs", "actions", "rewards", "next_obs", "dones")})
            n = len(batch["rewards"])
            consumed += n
            self._env_steps_total += n
        metrics: Dict[str, Any] = {"buffer_size": len(self.buffer),
                                   "env_steps_this_iter": consumed,
                                   "eps_ladder_min": self._runner_eps[-1],
                                   "eps_ladder_max": self._runner_eps[0]}
        if len(self.buffer) >= cfg.learning_starts:
            metrics["td_abs_mean"] = self._replay_updates(
                cfg.updates_per_iter or 16)
            metrics["num_updates"] = self._updates
        metrics.update(self.collect_episode_stats())
        return metrics

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
