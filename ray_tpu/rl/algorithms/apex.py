"""Ape-X DQN: distributed prioritized experience replay.

Reference analog: ``rllib/algorithms/apex_dqn/apex_dqn.py`` (Horgan et
al. 2018). The three Ape-X signatures, on this framework's primitives:

1. **Async sampling fleet** — runners keep producing fragments under
   slightly stale params (the IMPALA inflight-refs pipeline, not the
   synchronous DQN gather), so the learner never waits on the slowest
   actor.
2. **Per-actor epsilon ladder** — runner ``i`` of ``N`` explores with
   ``eps_i = base ** (1 + 7 * i / (N - 1))`` (the paper's schedule): a
   few runners stay near-greedy while others explore hard, replacing the
   single annealed epsilon.
3. **Prioritized replay always on** — new fragments enter the buffer at
   max priority; sampled minibatches update priorities from the TD error
   (inherited from the DQN learner's ``td`` output).
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rl.algorithms.ddpg import DDPG
from ray_tpu.rl.algorithms.dqn import DQN
from ray_tpu.rl.config import AlgorithmConfig


class ApexDQNConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=ApexDQN, **kwargs)
        self.lr = 1e-3
        self.minibatch_size = 64
        self.num_env_runners = 4
        self.prioritized_replay = True
        self.apex_eps_base = 0.4
        self.apex_eps_alpha = 7.0
        self.updates_per_iter = 16


class _ApexFleet:
    """The Ape-X actor/learner decoupling, shared by the DQN and DDPG
    variants: an async inflight pipeline (runners resample immediately
    under slightly stale params) feeding a prioritized buffer. Subclasses
    provide ``_params_for(runner_i)`` (the exploration ladder) and the
    learner-side ``_replay_updates`` (from their base algorithm)."""

    # consecutive failures before a runner is dropped from the rotation —
    # a runner past max_restarts fails its refs INSTANTLY, and resubmitting
    # to it forever would win every wait() and starve live runners
    _MAX_CONSECUTIVE_FAILURES = 3

    def _init_fleet(self) -> None:
        self._inflight: Dict[Any, Any] = {}
        self._runner_failures: Dict[int, int] = {}

    def _submit(self, runner_i: int) -> None:
        if self._runner_failures.get(runner_i, 0) \
                >= self._MAX_CONSECUTIVE_FAILURES:
            return  # evicted from rotation
        runner = self.runners[runner_i]
        ref = runner.sample.remote(self._params_for(runner_i))
        self._inflight[ref] = runner_i

    def _store_batch(self, batch) -> None:
        self.buffer.add_batch(
            {k: batch[k] for k in ("obs", "actions", "rewards",
                                   "next_obs", "dones")})

    def _consume_round(self) -> int:
        """Pump one round of fragments into the buffer; dead runners'
        fragments are dropped and the (restarting) runner resubmitted."""
        submitted = set(self._inflight.values())
        for i in range(len(self.runners)):
            if i not in submitted:
                self._submit(i)
        if not self._inflight:
            raise RuntimeError(
                "all env-runners failed permanently (each exceeded "
                f"{self._MAX_CONSECUTIVE_FAILURES} consecutive failures)")
        consumed = 0
        for _ in range(len(self.runners)):
            if not self._inflight:
                break
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
            ref = ready[0]
            runner_i = self._inflight.pop(ref)
            try:
                batch = ray_tpu.get(ref)
            except Exception:  # noqa: BLE001 — fragment lost, not fatal
                self._runner_failures[runner_i] = \
                    self._runner_failures.get(runner_i, 0) + 1
                self._submit(runner_i)
                continue
            self._runner_failures.pop(runner_i, None)
            self._submit(runner_i)  # resubmit with fresh params
            self._store_batch(batch)
            n = len(batch["rewards"])
            consumed += n
            self._env_steps_total += n
        return consumed

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()


class ApexDQN(_ApexFleet, DQN):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ApexDQNConfig()

    def build_learner(self) -> None:
        cfg = self.config
        if not cfg.prioritized_replay:
            raise ValueError("ApexDQN requires prioritized_replay=True "
                             "(it IS the algorithm)")
        super().build_learner()
        self._init_fleet()
        # epsilon ladder: runner i's exploration is fixed, not annealed
        n = max(1, len(self.runners))
        base, alpha = cfg.apex_eps_base, cfg.apex_eps_alpha
        self._runner_eps = [
            base ** (1 + alpha * i / max(1, n - 1)) for i in range(n)]

    def _params_for(self, runner_i: int):
        return self._runner_params(epsilon=self._runner_eps[runner_i])

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        consumed = self._consume_round()
        metrics: Dict[str, Any] = {"buffer_size": len(self.buffer),
                                   "env_steps_this_iter": consumed,
                                   "eps_ladder_min": self._runner_eps[-1],
                                   "eps_ladder_max": self._runner_eps[0]}
        if len(self.buffer) >= cfg.learning_starts:
            metrics["td_abs_mean"] = self._replay_updates(
                cfg.updates_per_iter or 16)
            metrics["num_updates"] = self._updates
        metrics.update(self.collect_episode_stats())
        return metrics


class ApexDDPGConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=ApexDDPG, **kwargs)
        self.env = "Pendulum-v1"
        self.lr = 1e-3
        self.minibatch_size = 256
        self.num_env_runners = 4
        self.prioritized_replay = True
        self.policy_delay = 1
        self.target_noise = 0.0
        self.updates_per_iter = 16
        # per-actor gaussian noise ladder (continuous-action analog of the
        # epsilon ladder): sigma_i = base ** (1 + alpha * i / (N - 1))
        self.apex_sigma_base = 0.4
        self.apex_sigma_alpha = 3.0


class ApexDDPG(_ApexFleet, DDPG):
    """Ape-X DDPG: the distributed prioritized-replay harness around the
    deterministic-policy-gradient learner (reference analog:
    ``rllib/algorithms/apex_ddpg/apex_ddpg.py``). Same three Ape-X
    signatures as the DQN variant; exploration diversity comes from a
    per-actor gaussian-noise ladder instead of epsilon."""

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ApexDDPGConfig()

    def build_learner(self) -> None:
        cfg = self.config
        if not cfg.prioritized_replay:
            raise ValueError("ApexDDPG requires prioritized_replay=True "
                             "(it IS the algorithm)")
        super().build_learner()
        self._init_fleet()
        n = max(1, len(self.runners))
        base, alpha = cfg.apex_sigma_base, cfg.apex_sigma_alpha
        self._runner_sigmas = [
            base ** (1 + alpha * i / max(1, n - 1)) for i in range(n)]

    def _params_for(self, runner_i: int):
        return self._runner_params(sigma=self._runner_sigmas[runner_i])

    def _store_batch(self, batch) -> None:
        # replay the EXECUTED (noisy, clipped) action — the critic's TD
        # target must condition on what actually hit the env
        self.buffer.add_batch(
            {"obs": batch["obs"], "actions": batch["actions_executed"],
             "rewards": batch["rewards"], "next_obs": batch["next_obs"],
             "dones": batch["dones"]})

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        consumed = self._consume_round()
        metrics: Dict[str, Any] = {"buffer_size": len(self.buffer),
                                   "env_steps_this_iter": consumed,
                                   "sigma_ladder_min": self._runner_sigmas[-1],
                                   "sigma_ladder_max": self._runner_sigmas[0]}
        if len(self.buffer) >= cfg.learning_starts:
            metrics.update(self._replay_updates(cfg.updates_per_iter or 16))
            metrics["num_updates"] = self._updates
        metrics.update(self.collect_episode_stats())
        return metrics
