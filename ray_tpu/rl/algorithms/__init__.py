from ray_tpu.rl.algorithms.a2c import A2C, A2CConfig  # noqa: F401
from ray_tpu.rl.algorithms.alphazero import (  # noqa: F401
    AlphaZero,
    AlphaZeroConfig,
    Game,
    MCTS,
    TicTacToe,
)
from ray_tpu.rl.algorithms.apex import (  # noqa: F401
    ApexDDPG,
    ApexDDPGConfig,
    ApexDQN,
    ApexDQNConfig,
)
from ray_tpu.rl.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.ars import ARS, ARSConfig  # noqa: F401
from ray_tpu.rl.algorithms.bandits import (  # noqa: F401
    BanditConfig,
    BanditLinTS,
    BanditLinUCB,
    LinearBanditEnv,
)
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rl.algorithms.crr import CRR, CRRConfig  # noqa: F401
from ray_tpu.rl.algorithms.dreamer import (  # noqa: F401
    DreamerV3,
    DreamerV3Config,
)
from ray_tpu.rl.algorithms.dt import DT, DTConfig  # noqa: F401
from ray_tpu.rl.algorithms.lc0 import (  # noqa: F401
    ConnectFour,
    LeelaChessZero,
    LeelaChessZeroConfig,
)
from ray_tpu.rl.algorithms.maddpg import MADDPG, MADDPGConfig  # noqa: F401
from ray_tpu.rl.algorithms.mbmpo import MBMPO, MBMPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.maml import (  # noqa: F401
    MAML,
    MAMLConfig,
    PointGoal,
)
from ray_tpu.rl.algorithms.pg import PG, PGConfig  # noqa: F401
from ray_tpu.rl.algorithms.ddpg import (  # noqa: F401
    DDPG,
    DDPGConfig,
    TD3,
    TD3Config,
)
from ray_tpu.rl.algorithms.dqn import (  # noqa: F401
    DQN,
    DQNConfig,
    SimpleQ,
    SimpleQConfig,
)
from ray_tpu.rl.algorithms.es import ES, ESConfig  # noqa: F401
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rl.algorithms.offline import (  # noqa: F401
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.qmix import QMIX, QMIXConfig  # noqa: F401
from ray_tpu.rl.algorithms.r2d2 import (  # noqa: F401
    MaskedCartPole,
    R2D2,
    R2D2Config,
)
from ray_tpu.rl.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.algorithms.slateq import (  # noqa: F401
    RecSlateEnv,
    SlateQ,
    SlateQConfig,
)
