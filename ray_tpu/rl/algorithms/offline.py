"""Offline RL: BC (behavior cloning) and MARWIL (advantage-weighted BC).

Reference analogs: ``rllib/algorithms/bc/`` and ``rllib/algorithms/marwil/``
(BC is MARWIL with beta=0 there too). Training consumes a fixed dataset —
a dict of arrays (obs, actions, and for MARWIL rewards/dones for
monte-carlo returns) or a ``ray_tpu.data.Dataset`` of such rows — with no
environment interaction; the env is only probed for the spec.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


def _to_arrays(data) -> Dict[str, np.ndarray]:
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    from ray_tpu.data import Dataset

    if isinstance(data, Dataset):
        rows = data.take_all()
        return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
    raise TypeError(f"offline_data must be a dict of arrays or a "
                    f"ray_tpu.data.Dataset, got {type(data)}")


def _mc_returns(rewards, dones, gamma, env_ids=None) -> np.ndarray:
    """Per-step discounted return to the end of each episode. Rows from a
    VECTORIZED rollout interleave env streams — pass ``env_ids`` (per-row
    env index) so each stream accumulates independently; without it, rows
    are assumed to be one time-ordered episode stream."""
    out = np.zeros_like(rewards, dtype=np.float64)
    if env_ids is None:
        acc = 0.0
        for i in range(len(rewards) - 1, -1, -1):
            if dones[i]:
                acc = 0.0
            acc = rewards[i] + gamma * acc
            out[i] = acc
        return out.astype(np.float32)
    accs: Dict[Any, float] = {}
    for i in range(len(rewards) - 1, -1, -1):
        e = env_ids[i]
        acc = 0.0 if dones[i] else accs.get(e, 0.0)
        acc = rewards[i] + gamma * acc
        accs[e] = acc
        out[i] = acc
    return out.astype(np.float32)


class MARWIL(Algorithm):
    """beta > 0: exp(beta * normalized advantage) weighted cloning with a
    learned value baseline; beta == 0 degenerates to plain BC."""

    need_env_runners = False
    beta_override = None  # BC subclass pins 0.0

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.num_epochs = 1
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        beta = self.beta_override if self.beta_override is not None else cfg.beta
        vf_coeff = cfg.vf_coeff

        if cfg.offline_data is None:
            raise ValueError(f"{type(self).__name__} needs config.offline_data")
        self._data = _to_arrays(cfg.offline_data)
        if beta > 0 and "returns" not in self._data:
            self._data["returns"] = _mc_returns(
                self._data["rewards"], self._data["dones"], cfg.gamma,
                env_ids=self._data.get("env_ids"))

        def loss_fn(params, batch, key):
            logits = models.policy_logits(params, batch["obs"])
            if spec.discrete:
                logp = models.categorical_logp(logits, batch["actions"])
            else:
                logp = models.gaussian_logp(logits, params["log_std"],
                                            batch["actions"])
            if beta > 0:
                values = models.value(params, batch["obs"])
                adv = batch["returns"] - values
                vf_loss = jnp.mean(adv ** 2)
                w = jnp.exp(beta * jax.lax.stop_gradient(
                    adv / (jnp.std(adv) + 1e-8)))
                w = jnp.minimum(w, 20.0)  # exp weight clamp (reference c)
                pi_loss = -jnp.mean(w * logp)
                total = pi_loss + vf_coeff * vf_loss
                return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                               "weight_mean": w.mean()}
            pi_loss = -jnp.mean(logp)
            return pi_loss, {"pi_loss": pi_loss}

        params = models.init_policy(jax.random.key(cfg.seed), spec,
                                    cfg.hidden)
        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self._data["obs"])
        idx = self._rng.permutation(n)
        metrics: Dict[str, Any] = {}
        for _ in range(max(1, cfg.num_epochs)):
            for lo in range(0, n, cfg.minibatch_size):
                rows = idx[lo:lo + cfg.minibatch_size]
                mb = {k: v[rows] for k, v in self._data.items()}
                metrics = self.learner.update_minibatch(mb)
        self._env_steps_total += n
        out = {k: float(v) for k, v in metrics.items()}
        out["samples_this_iter"] = n
        return out

    def evaluate(self, num_episodes: int = 5) -> Dict[str, float]:
        """Greedy rollout of the cloned policy in the (probe) env."""
        from ray_tpu.rl.env import make_env

        env = make_env(self.config.env, 1, self.config.env_config)
        params = self.learner.get_params()
        returns = []
        obs = env.reset()
        ep_ret, done_count, steps = 0.0, 0, 0
        while done_count < num_episodes and steps < 100_000:
            logits = models.policy_logits(params, jnp.asarray(obs))
            if self.spec.discrete:
                action = np.asarray(jnp.argmax(logits, axis=-1))
            else:
                action = np.clip(np.asarray(logits),
                                 self.spec.action_low, self.spec.action_high)
            obs, reward, done = env.step(action)
            ep_ret += float(reward[0])
            steps += 1
            if done[0]:
                returns.append(ep_ret)
                ep_ret = 0.0
                done_count += 1
        return {"episode_return_mean": float(np.mean(returns or [0.0]))}


class BC(MARWIL):
    """Plain behavior cloning (MARWIL with beta = 0)."""

    beta_override = 0.0


class MARWILConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=MARWIL, **kwargs)
        self.num_epochs = 1


class BCConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=BC, **kwargs)
        self.num_epochs = 1
