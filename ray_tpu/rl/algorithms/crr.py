"""CRR: critic-regularized regression for offline RL.

Reference analog: ``rllib/algorithms/crr/crr.py`` (Wang et al. 2020 —
"Critic Regularized Regression"). An offline actor-critic: the critic is
a plain TD(0) ensemble with polyak targets, and the actor is weighted
behavior cloning where the weight is a function of the advantage

    A(s, a) = Q(s, a) - E_{a'~pi}[Q(s, a')]

estimated with ``crr_num_actions`` policy samples. Two weightings from
the paper, selected by ``crr_weight_type``:

- ``"bin"``:  w = 1[A > 0]           (binary filter; "bin_max" in rllib)
- ``"exp"``:  w = clip(exp(A/beta))  (exponential, like AWAC/MARWIL)

Everything is one jitted update over offline minibatches — no env
interaction (the env is probed only for spaces, like BC/MARWIL/CQL).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.algorithms.offline import _to_arrays
from ray_tpu.rl.algorithms.sac import _squashed_sample_logp
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


class CRRConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=CRR, **kwargs)
        self.env = "Pendulum-v1"
        self.minibatch_size = 256
        self.crr_beta = 1.0          # exp-weight temperature
        self.crr_num_actions = 4     # policy samples for E[Q(s, a')]
        self.crr_weight_type = "exp"  # "exp" | "bin"
        self.crr_weight_clip = 20.0
        self.updates_per_iter = 50


class CRR(Algorithm):
    need_env_runners = False  # offline: the dataset IS the experience

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return CRRConfig()

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        if spec.discrete:
            raise ValueError("CRR here targets continuous control; use "
                             "BC/MARWIL or DQN-family for discrete")
        if cfg.offline_data is None:
            raise ValueError("CRR needs config.offline_data")
        self._data = _to_arrays(cfg.offline_data)
        for col in ("obs", "actions", "rewards", "next_obs", "dones"):
            if col not in self._data:
                raise ValueError(f"offline_data missing {col!r}")
        self._n = len(self._data["rewards"])
        self._rng = np.random.default_rng(cfg.seed)

        gamma, tau = cfg.gamma, cfg.tau
        low, high = spec.action_low, spec.action_high
        adim = spec.action_dim
        n_samp = cfg.crr_num_actions
        beta = cfg.crr_beta
        w_type = cfg.crr_weight_type
        w_clip = cfg.crr_weight_clip
        if w_type not in ("exp", "bin"):
            raise ValueError(f"crr_weight_type must be 'exp' or 'bin', "
                             f"got {w_type!r}")

        key = jax.random.key(cfg.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        qin = spec.obs_dim + adim
        q1 = models.init_mlp(k_q1, [qin, *cfg.hidden, 1], out_scale=1.0)
        q2 = models.init_mlp(k_q2, [qin, *cfg.hidden, 1], out_scale=1.0)
        pi = models.init_mlp(
            k_pi, [spec.obs_dim, *cfg.hidden, 2 * adim], out_scale=0.01)
        params = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_target": jax.tree_util.tree_map(jnp.copy, q1),
            "q2_target": jax.tree_util.tree_map(jnp.copy, q2),
        }

        def pi_dist(pi_params, obs):
            out = models.mlp_forward(pi_params, obs)
            return jnp.split(out, 2, axis=-1)

        def q_val(q_params, obs, act):
            return models.mlp_forward(
                q_params, jnp.concatenate([obs, act], axis=-1))[..., 0]

        def loss_fn(params, batch, key):
            k1, k2 = jax.random.split(key)
            obs, nobs = batch["obs"], batch["next_obs"]
            acts = batch["actions"]
            # --- critic: TD(0) toward min of target ensemble, with the
            # next action drawn from the CURRENT policy (the paper's
            # policy-evaluation critic; no entropy term unlike SAC/CQL) ---
            nmean, nlogstd = pi_dist(params["pi"], nobs)
            nact, _ = _squashed_sample_logp(nmean, nlogstd, k1, low, high)
            qt = jnp.minimum(q_val(params["q1_target"], nobs, nact),
                             q_val(params["q2_target"], nobs, nact))
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterminal * qt)
            q1_pred = q_val(params["q1"], obs, acts)
            q2_pred = q_val(params["q2"], obs, acts)
            critic_loss = jnp.mean((q1_pred - target) ** 2) + \
                jnp.mean((q2_pred - target) ** 2)
            # --- advantage estimate: A = Q(s, a_data) - mean_j Q(s, a_j) ---
            mean, log_std = pi_dist(params["pi"], obs)
            samp, _ = _squashed_sample_logp(
                jnp.broadcast_to(mean, (n_samp,) + mean.shape),
                jnp.broadcast_to(log_std, (n_samp,) + log_std.shape),
                k2, low, high)
            rep = jnp.broadcast_to(obs, (n_samp,) + obs.shape)
            q_samp = jnp.minimum(q_val(params["q1"], rep, samp),
                                 q_val(params["q2"], rep, samp))
            q_data = jnp.minimum(q1_pred, q2_pred)
            adv = jax.lax.stop_gradient(q_data - q_samp.mean(axis=0))
            if w_type == "bin":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.minimum(jnp.exp(adv / beta), w_clip)
            # --- actor: advantage-filtered log-likelihood of data actions
            # (squashed-gaussian logp of the dataset action) ---
            eps = 1e-6
            span = (high - low) / 2.0
            mid = (high + low) / 2.0
            pre = jnp.arctanh(jnp.clip((acts - mid) / span,
                                       -1 + eps, 1 - eps))
            std = jnp.exp(jnp.clip(log_std, -10.0, 2.0))
            base_logp = jnp.sum(
                -0.5 * ((pre - mean) / std) ** 2 - jnp.log(std)
                - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
            # tanh-squash correction
            base_logp -= jnp.sum(
                jnp.log(span * (1 - jnp.tanh(pre) ** 2) + eps), axis=-1)
            pi_loss = -jnp.mean(w * base_logp)
            total = critic_loss + pi_loss
            return total, {"critic_loss": critic_loss, "pi_loss": pi_loss,
                           "adv_mean": adv.mean(), "weight_mean": w.mean()}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def polyak(params):
            new = dict(params)
            for src, dst in (("q1", "q1_target"), ("q2", "q2_target")):
                new[dst] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    params[dst], params[src])
            return new

        self._polyak = polyak

        @jax.jit
        def act_greedy(params, obs):
            mean, _ = pi_dist(params["pi"], obs)
            mid = (high + low) / 2.0
            span = (high - low) / 2.0
            return mid + span * jnp.tanh(mean)

        self._act_greedy = act_greedy

    def _minibatch(self, size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._n, size=min(size, self._n))
        return {k: v[idx] for k, v in self._data.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        m: Dict[str, Any] = {}
        for _ in range(cfg.updates_per_iter or 50):
            m = self.learner.update_minibatch(
                self._minibatch(cfg.minibatch_size))
            self.learner.params = self._polyak(self.learner.params)
        self._env_steps_total += 0  # offline: no env interaction
        return {k: float(v) for k, v in m.items()}

    def evaluate(self, num_episodes: int = 5) -> Dict[str, float]:
        """Greedy (tanh-mean) rollout in the probe env."""
        from ray_tpu.rl.env import make_env

        env = make_env(self.config.env, 1, self.config.env_config)
        params = self.learner.get_params()
        returns = []
        obs = env.reset()
        ep_ret, done_count, steps = 0.0, 0, 0
        while done_count < num_episodes and steps < 100_000:
            action = np.asarray(self._act_greedy(params, jnp.asarray(obs)))
            obs, reward, done = env.step(action)
            ep_ret += float(reward[0])
            steps += 1
            if done[0]:
                returns.append(ep_ret)
                ep_ret = 0.0
                done_count += 1
        return {"episode_return_mean": float(np.mean(returns or [0.0]))}
