"""DQN with double-Q, target network, and optional prioritized replay.

Reference analog: ``rllib/algorithms/dqn/`` (+ ``utils/replay_buffers``).
The Q-net reuses the policy MLP ("pi" head emits Q-values); exploration is
epsilon-greedy on the EnvRunner fleet with the epsilon schedule riding in
the params pytree (no recompiles).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


class DQN(Algorithm):
    explore_mode = "epsilon_greedy"

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.lr = 1e-3
        cfg.minibatch_size = 64
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        gamma, double_q = cfg.gamma, cfg.double_q

        def loss_fn(params, batch, key):
            q = models.policy_logits(params["q"], batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            q_next_t = models.policy_logits(params["target"],
                                            batch["next_obs"])
            if double_q:
                q_next_online = models.policy_logits(params["q"],
                                                     batch["next_obs"])
                best = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_t, best[..., None], axis=-1)[..., 0]
            else:
                q_next = jnp.max(q_next_t, axis=-1)
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = batch["rewards"] + gamma * nonterminal \
                * jax.lax.stop_gradient(q_next)
            td = q_taken - target
            weights = batch.get("weights", jnp.ones_like(td))
            loss = jnp.mean(weights * td ** 2)
            return loss, {"td_abs_mean": jnp.mean(jnp.abs(td)),
                          "q_mean": jnp.mean(q_taken),
                          "td": jax.lax.stop_gradient(td)}

        init_q = self.init_policy_params()
        params = {"q": init_q, "target": jax.tree_util.tree_map(
            jnp.copy, init_q)}
        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        # the target net takes zero grads through the stop_gradient, but
        # adam's eps term would still drift it — training_step restores it
        # after every update and hard-syncs on the schedule instead
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        if cfg.prioritized_replay:
            self.buffer = buf_cls(cfg.buffer_size, alpha=cfg.replay_alpha,
                                  beta=cfg.replay_beta, seed=cfg.seed)
        else:
            self.buffer = buf_cls(cfg.buffer_size, seed=cfg.seed)
        self._updates = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_total / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _runner_params(self, epsilon: float = None):
        p = self.learner.get_params()
        eps = self._epsilon() if epsilon is None else epsilon
        return {"pi": p["q"]["pi"], "vf": p["q"]["vf"],
                "epsilon": jnp.asarray(eps)}

    def _eval_params(self):
        """Greedy Q-policy (epsilon off) for Algorithm.evaluate."""
        return {**self._runner_params(), "epsilon": jnp.asarray(0.0)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.synchronous_sample(self._runner_params())
        self.buffer.add_batch(
            {k: batch[k] for k in
             ("obs", "actions", "rewards", "next_obs", "dones")})
        metrics: Dict[str, Any] = {"epsilon": self._epsilon(),
                                   "buffer_size": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            num_updates = (cfg.updates_per_iter or
                           max(1, len(batch["rewards"]) // cfg.minibatch_size))
            metrics["td_abs_mean"] = self._replay_updates(num_updates)
            metrics["num_updates"] = self._updates
        metrics.update(self.collect_episode_stats())
        return metrics

    def _replay_updates(self, num_updates: int) -> float:
        """The shared DQN-family update loop (also Ape-X): prioritized or
        uniform minibatches, target restored after each step (adam's eps
        term would drift it through the zero-grad path), priorities
        refreshed from TD error, periodic hard target sync. Returns the
        mean |TD|."""
        cfg = self.config
        td_list = []
        for _ in range(num_updates):
            target_before = self.learner.params["target"]
            if cfg.prioritized_replay:
                sample, idx, weights = self.buffer.sample(
                    cfg.minibatch_size)
                sample = dict(sample, weights=weights)
            else:
                sample = self.buffer.sample(cfg.minibatch_size)
            m = self.learner.update_minibatch(sample)
            # target net is updated only by periodic hard sync
            self.learner.params = dict(self.learner.params,
                                       target=target_before)
            if cfg.prioritized_replay:
                self.buffer.update_priorities(idx, np.asarray(m["td"]))
            td_list.append(float(m["td_abs_mean"]))
            self._updates += 1
            if self._updates % cfg.target_update_freq == 0:
                self.learner.params = dict(
                    self.learner.params,
                    target=jax.tree_util.tree_map(
                        jnp.copy, self.learner.params["q"]))
        return float(np.mean(td_list))

    def get_extra_state(self):
        return {"updates": self._updates}

    def set_extra_state(self, state) -> None:
        if state:
            self._updates = state.get("updates", 0)


class DQNConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=DQN, **kwargs)
        self.lr = 1e-3
        self.minibatch_size = 64


class SimpleQ(DQN):
    """Reference ``rllib/algorithms/simple_q``: DQN stripped of the
    DQN-paper add-ons (no double-Q, no prioritized replay). A real
    class — not a registry alias to DQN — so checkpoints, ``rt rl
    train --run SIMPLEQ`` output and ``type(algo).__name__`` all report
    the algorithm that actually ran."""


class SimpleQConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=SimpleQ, **kwargs)
        self.lr = 1e-3
        self.minibatch_size = 64
        self.double_q = False
        self.prioritized_replay = False
