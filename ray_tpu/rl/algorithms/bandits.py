"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference analog: ``rllib/algorithms/bandit/`` (``BanditLinUCB``,
``BanditLinTS`` — disjoint linear models per arm, trained online).
Redesigned vectorized: the per-arm ridge statistics (A = λI + Σ x xᵀ,
b = Σ r x) update in closed form from whole context batches, so one
training_step consumes a [N, d] batch instead of stepping singly.

``LinearBandit-v0`` (registered here) is the synthetic benchmark: contexts
~ N(0, I), true per-arm weights, reward = θ_aᵀx + noise — regret against
the known optimum is the convergence gate.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import EnvSpec, VectorEnv, make_env, register_env


class LinearBanditEnv(VectorEnv):
    """One-step contextual bandit: every step is an episode."""

    def __init__(self, num_envs: int, seed: int = 0, context_dim: int = 8,
                 num_arms: int = 4, noise: float = 0.1):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._d, self._k, self._noise = context_dim, num_arms, noise
        # fixed hidden arm weights — the thing the learner must recover
        self.theta = np.random.default_rng(12345).standard_normal(
            (num_arms, context_dim)) / np.sqrt(context_dim)
        self.spec = EnvSpec(obs_dim=context_dim, num_actions=num_arms)
        self._ctx = self._draw()

    def _draw(self) -> np.ndarray:
        return self._rng.standard_normal(
            (self.num_envs, self._d)).astype(np.float32)

    def reset(self) -> np.ndarray:
        self._ctx = self._draw()
        return self._ctx

    def step(self, actions: np.ndarray):
        a = np.asarray(actions).reshape(self.num_envs)
        means = np.einsum("nd,nd->n", self._ctx, self.theta[a])
        rewards = (means + self._noise * self._rng.standard_normal(
            self.num_envs)).astype(np.float32)
        dones = np.ones(self.num_envs, dtype=bool)
        self._ctx = self._draw()
        return self._ctx, rewards, dones

    def best_mean_reward(self, contexts: np.ndarray) -> np.ndarray:
        return (contexts @ self.theta.T).max(axis=1)


register_env("LinearBandit-v0",
             lambda c: LinearBanditEnv(c["num_envs"], seed=c.get("seed", 0),
                                       context_dim=c.get("context_dim", 8),
                                       num_arms=c.get("num_arms", 4),
                                       noise=c.get("noise", 0.1)))


class BanditConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=None, **kwargs)
        self.env = "LinearBandit-v0"
        self.ucb_alpha = 1.0        # exploration width (LinUCB)
        self.ridge_lambda = 1.0
        self.ts_scale = 0.5         # posterior scale (LinTS)
        self.steps_per_iter = 32    # env batches per training_step


class _LinearBandit(Algorithm):
    """Shared machinery: per-arm ridge stats + pluggable arm scoring."""

    need_env_runners = False  # closed-form online updates, env in-process

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = BanditConfig()
        cfg.algo_class = cls
        return cfg

    def build_learner(self) -> None:
        cfg = self.config
        self._env = make_env(cfg.env, cfg.num_envs_per_runner,
                             cfg.env_config, seed=cfg.seed)
        spec = self._env.spec
        if not spec.discrete or spec.obs_dim <= 0:
            raise ValueError("bandits need discrete arms over flat contexts")
        d, k = spec.obs_dim, spec.num_actions
        lam = cfg.ridge_lambda
        self._A_inv = np.stack([np.eye(d) / lam for _ in range(k)])
        self._b = np.zeros((k, d))
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self._env.reset()
        self._cum_reward = 0.0
        self._cum_regret = 0.0
        self.learner = self

    # Algorithm checkpoint surface
    def get_params(self):
        return {"A_inv": self._A_inv, "b": self._b}

    def set_params(self, params) -> None:
        self._A_inv = np.asarray(params["A_inv"])
        self._b = np.asarray(params["b"])

    def _select_arms(self, ctx: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _theta_hat(self) -> np.ndarray:
        return np.einsum("kde,ke->kd", self._A_inv, self._b)

    def _update(self, ctx: np.ndarray, arms: np.ndarray,
                rewards: np.ndarray) -> None:
        """Sherman–Morrison per-row A⁻¹ update + b accumulation."""
        for x, a, r in zip(ctx, arms, rewards):
            Ai = self._A_inv[a]
            Ax = Ai @ x
            self._A_inv[a] = Ai - np.outer(Ax, Ax) / (1.0 + x @ Ax)
            self._b[a] += r * x

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy (exploitation-only) pulls with the fitted arm models;
        one 'episode' = one vectorized env batch."""
        theta = self._theta_hat()
        total, n = 0.0, 0
        obs = self._obs
        for _ in range(num_episodes):
            arms = np.argmax(obs @ theta.T, axis=-1)
            obs, r, _ = self._env.step(arms)
            total += float(np.sum(r))
            n += len(r)
        self._obs = obs
        return {"episodes": num_episodes,
                "episode_return_mean": total / max(1, n)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        regret_known = hasattr(self._env, "best_mean_reward")
        total_r, n = 0.0, 0
        for _ in range(cfg.steps_per_iter):
            ctx = self._obs
            arms = self._select_arms(ctx)
            if regret_known:
                best = self._env.best_mean_reward(ctx)
                chosen = np.einsum("nd,nd->n", ctx, self._env.theta[arms])
                self._cum_regret += float((best - chosen).sum())
            self._obs, rewards, _ = self._env.step(arms)
            self._update(ctx, arms, rewards)
            total_r += float(rewards.sum())
            n += len(rewards)
        self._env_steps_total += n
        self._cum_reward += total_r
        out = {"mean_reward": total_r / n,
               "cumulative_reward": self._cum_reward}
        if regret_known:
            out["cumulative_regret"] = self._cum_regret
            out["regret_per_step"] = self._cum_regret / max(
                1, self._env_steps_total)
        return out


class BanditLinUCB(_LinearBandit):
    """Disjoint LinUCB (Li et al. 2010): arm = argmax θ̂ᵀx + α√(xᵀA⁻¹x)."""

    def _select_arms(self, ctx: np.ndarray) -> np.ndarray:
        theta = self._theta_hat()                      # [k, d]
        means = ctx @ theta.T                          # [n, k]
        # width[n,k] = sqrt(x A_k^-1 x)
        widths = np.sqrt(np.maximum(
            np.einsum("nd,kde,ne->nk", ctx, self._A_inv, ctx), 0.0))
        return np.argmax(means + self.config.ucb_alpha * widths, axis=1)


class BanditLinTS(_LinearBandit):
    """Linear Thompson sampling: θ̃_k ~ N(θ̂_k, v² A_k⁻¹), arm = argmax
    θ̃ᵀx."""

    def _select_arms(self, ctx: np.ndarray) -> np.ndarray:
        theta = self._theta_hat()
        k, d = theta.shape
        sampled = np.empty_like(theta)
        for a in range(k):
            cov = self.config.ts_scale ** 2 * self._A_inv[a]
            sampled[a] = self._rng.multivariate_normal(theta[a], cov)
        return np.argmax(ctx @ sampled.T, axis=1)
