"""PG: vanilla policy gradient (REINFORCE).

Reference analog: ``rllib/algorithms/pg/pg.py`` — the minimal on-policy
baseline: ``loss = -mean(logp(a|s) * R)`` with monte-carlo returns and no
clipping, no value baseline, no multiple epochs. The config pins
``lambda_ = 1.0`` so the runner's GAE degenerates to monte-carlo returns
(the untouched value head stays near zero, so ``value_targets`` are the
discounted returns the reference uses).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


class PGConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=PG, **kwargs)
        self.lambda_ = 1.0     # monte-carlo returns, the REINFORCE target
        self.num_epochs = 1    # strictly on-policy: one pass, no reuse
        self.lr = 4e-3


class PG(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return PGConfig()

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        ent_coeff = cfg.entropy_coeff

        def loss_fn(params, batch, key):
            logits = models.policy_logits(params, batch["obs"])
            if spec.discrete:
                logp = models.categorical_logp(logits, batch["actions"])
                entropy = models.categorical_entropy(logits).mean()
            else:
                logp = models.gaussian_logp(logits, params["log_std"],
                                            batch["actions"])
                entropy = models.gaussian_entropy(params["log_std"])
            ret = batch["value_targets"]
            ret = (ret - ret.mean()) / (ret.std() + 1e-8)
            pi_loss = -jnp.mean(logp * ret)
            total = pi_loss - ent_coeff * entropy
            return total, {"pi_loss": pi_loss, "entropy": entropy}

        params = self.init_policy_params()
        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.synchronous_sample(self.learner.get_params())
        metrics = self.learner.update(
            batch, num_epochs=1, minibatch_size=cfg.minibatch_size or 0,
            seed=cfg.seed + self._iteration)
        result = dict(metrics)
        result.update(self.collect_episode_stats())
        result["env_steps_this_iter"] = len(batch["rewards"])
        return result
