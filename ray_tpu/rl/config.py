"""AlgorithmConfig: the fluent builder.

Reference analog: ``rllib/algorithms/algorithm_config.py`` — chainable
``.environment().env_runners().training().resources()`` producing an
Algorithm. Flat dict overrides (from Tune param spaces) map onto fields via
``update_from_dict``.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, Optional, Type


@dataclasses.dataclass
class AlgorithmConfig:
    algo_class: Optional[Type] = None
    # environment
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # env runners (sampling fleet)
    num_env_runners: int = 1
    num_envs_per_runner: int = 8
    rollout_fragment_length: int = 64
    num_cpus_per_runner: float = 1
    # runtime_env for the EnvRunner actors, e.g.
    # {"env_vars": {"JAX_PLATFORMS": "cpu"}} to pin the sampling fleet's
    # policy forward to host CPUs while the learner owns the chip
    # (BASELINE config 4's CPU-rollouts -> TPU-learner architecture).
    runner_runtime_env: Optional[dict] = None
    # Fleet fault tolerance (reference: FaultTolerantActorManager,
    # rllib/utils/actor_manager.py): runners restart on worker death and a
    # failed fragment is dropped for the iteration instead of killing the
    # training loop.
    restart_failed_env_runners: bool = True
    max_env_runner_restarts: int = 2
    # connector pipeline specs, e.g. ["mean_std_filter",
    # {"type": "clip_reward", "limit": 1.0}] (rl/connectors.py)
    connectors: Any = None
    # training
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    train_batch_size: int = 0          # 0 => runners * envs * fragment
    minibatch_size: int = 128
    num_epochs: int = 4
    grad_clip: float = 0.5
    hidden: tuple = (64, 64)
    seed: int = 0
    # PPO
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    kl_target: float = 0.0             # 0 disables adaptive-KL early stop
    # DQN
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_steps: int = 10_000
    target_update_freq: int = 500
    buffer_size: int = 100_000
    learning_starts: int = 1_000
    # replay-trained algos (DQN/SAC/DDPG/TD3): gradient updates per
    # training_step; 0 derives it from sampled-steps/minibatch (reference:
    # DQN's training_intensity ratio)
    updates_per_iter: int = 0
    double_q: bool = True
    prioritized_replay: bool = False
    replay_alpha: float = 0.6
    replay_beta: float = 0.4
    # SAC
    tau: float = 0.005
    initial_alpha: float = 0.2
    autotune_alpha: bool = True
    # IMPALA
    vtrace_clip_rho: float = 1.0
    vtrace_clip_pg_rho: float = 1.0
    # DDPG / TD3
    exploration_noise: float = 0.1
    policy_delay: int = 2              # TD3 delayed policy updates
    target_noise: float = 0.2          # TD3 target policy smoothing
    noise_clip: float = 0.5
    # offline RL (BC / MARWIL)
    offline_data: Any = None           # dict of arrays or ray_tpu.data Dataset
    beta: float = 1.0                  # MARWIL advantage temperature
    # model container: an rl_module.ModuleSpec routes param init through
    # the Catalog (custom encoder/activation); None = the default policy
    module_spec: Any = None
    # multi-agent
    policy_mapping_fn: Any = None      # agent_id -> policy_id (None = identity)
    # resources
    num_tpus_per_learner: float = 0
    num_learners: int = 0              # 0 => learner runs in the algo process

    # ---- fluent builders ----

    def environment(self, env: str, env_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    num_cpus_per_runner: Optional[float] = None,
                    connectors: Optional[list] = None,
                    runner_runtime_env: Optional[dict] = None
                    ) -> "AlgorithmConfig":
        for k, v in (("num_env_runners", num_env_runners),
                     ("num_envs_per_runner", num_envs_per_runner),
                     ("rollout_fragment_length", rollout_fragment_length),
                     ("num_cpus_per_runner", num_cpus_per_runner),
                     ("connectors", connectors),
                     ("runner_runtime_env", runner_runtime_env)):
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        return self.update_from_dict(kwargs)

    def resources(self, num_tpus_per_learner: Optional[float] = None,
                  num_learners: Optional[int] = None) -> "AlgorithmConfig":
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if k == "lambda":
                k = "lambda_"
            if not hasattr(self, k):
                raise ValueError(f"unknown config key {k!r}")
            setattr(self, k, v)
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    @property
    def effective_train_batch_size(self) -> int:
        if self.train_batch_size:
            return self.train_batch_size
        return (max(1, self.num_env_runners) * self.num_envs_per_runner
                * self.rollout_fragment_length)

    def multi_agent(self, *, policy_mapping_fn=None) -> "AlgorithmConfig":
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self):
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc.")
        return self.algo_class({"__algo_config": self.copy()})
