"""Pure-JAX vectorized environments for mesh-fused (Anakin) rollouts.

The device-side twin of ``rl/env.py``: the same classic-control dynamics
re-expressed as *pure functions* over explicit state pytrees, so a whole
rollout compiles into one XLA program — ``lax.scan`` over T steps,
``vmap`` over B env copies, zero host↔device ping-pong per step (the
Podracer/Anakin architecture, arxiv 2104.06272).

Parity contract with the host envs: ``JaxCartPole.step`` applies the
SAME Euler-integrated dynamics, termination bounds, and +1/step reward
as ``env.CartPole`` (float32 instead of float64 — tests assert
trajectory agreement to ~1e-4 over a fragment). Auto-reset on done uses
a jax.random key carried in the state, matching the host env's
re-randomized [-0.05, 0.05] init.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rl.env import EnvSpec


class CartPoleState(NamedTuple):
    """Per-env-copy state pytree (leading axis = env batch after vmap)."""

    x: jax.Array        # [4] physical state (x, x_dot, theta, theta_dot)
    t: jax.Array        # scalar int32 step count in the episode
    key: jax.Array      # per-env PRNG key driving auto-reset inits


class JaxCartPole:
    """CartPole-v1 dynamics as pure jittable functions.

    All methods are static over a single env copy; callers ``vmap`` them
    over the batch axis (``reset_batch`` does it for you). No Python
    state — the env "instance" only carries the spec.
    """

    spec = EnvSpec(obs_dim=4, num_actions=2)

    # dynamics constants — identical to env.CartPole
    _GRAVITY, _MC, _MP = 9.8, 1.0, 0.1
    _L, _FMAG, _DT = 0.5, 10.0, 0.02
    _THETA_LIM = 12 * 2 * jnp.pi / 360
    _X_LIM = 2.4
    _MAX_T = 500

    @classmethod
    def reset(cls, key: jax.Array) -> Tuple[CartPoleState, jax.Array]:
        """One env copy: fresh state + its observation."""
        key, sub = jax.random.split(key)
        x = jax.random.uniform(sub, (4,), jnp.float32, -0.05, 0.05)
        state = CartPoleState(x=x, t=jnp.zeros((), jnp.int32), key=key)
        return state, x

    @classmethod
    def reset_batch(cls, key: jax.Array, num_envs: int
                    ) -> Tuple[CartPoleState, jax.Array]:
        """B independent env copies: batched state pytree + obs [B, 4]."""
        keys = jax.random.split(key, num_envs)
        return jax.vmap(cls.reset)(keys)

    @classmethod
    def step(cls, state: CartPoleState, action: jax.Array
             ) -> Tuple[CartPoleState, jax.Array, jax.Array, jax.Array]:
        """One env copy, one transition: (state', obs', reward, done).

        Done envs are already reset in ``state'`` (the returned obs is
        the POST-reset observation, matching the host ``VectorEnv.step``
        contract); the reward/done flags describe the transition that
        ended.
        """
        x, x_dot, th, th_dot = state.x
        force = jnp.where(action == 1, cls._FMAG, -cls._FMAG)
        cos, sin = jnp.cos(th), jnp.sin(th)
        total_m = cls._MC + cls._MP
        pm_l = cls._MP * cls._L
        temp = (force + pm_l * th_dot ** 2 * sin) / total_m
        th_acc = (cls._GRAVITY * sin - cos * temp) / (
            cls._L * (4.0 / 3.0 - cls._MP * cos ** 2 / total_m))
        x_acc = temp - pm_l * th_acc * cos / total_m
        x = x + cls._DT * x_dot
        x_dot = x_dot + cls._DT * x_acc
        th = th + cls._DT * th_dot
        th_dot = th_dot + cls._DT * th_acc
        nxt = jnp.stack([x, x_dot, th, th_dot])
        t = state.t + 1
        done = ((jnp.abs(x) > cls._X_LIM)
                | (jnp.abs(th) > cls._THETA_LIM)
                | (t >= cls._MAX_T))
        reward = jnp.float32(1.0)
        # auto-reset: branchless select between the stepped state and a
        # fresh init (both sides compute — cheap at this state size, and
        # the select keeps the whole step traceable with static shapes)
        key, sub = jax.random.split(state.key)
        fresh = jax.random.uniform(sub, (4,), jnp.float32, -0.05, 0.05)
        nxt = jnp.where(done, fresh, nxt)
        t = jnp.where(done, jnp.zeros((), jnp.int32), t)
        new_state = CartPoleState(x=nxt, t=t, key=key)
        return new_state, nxt, reward, done

    @classmethod
    def step_batch(cls, state: CartPoleState, actions: jax.Array):
        """Batched transition: vmapped ``step`` over the env axis."""
        return jax.vmap(cls.step)(state, actions)

    @classmethod
    def from_host_state(cls, x, key: jax.Array, t=None) -> CartPoleState:
        """Adopt a host env's raw state [B, 4] (parity tests drive the
        numpy and JAX dynamics from the same initial conditions)."""
        x = jnp.asarray(x, jnp.float32)
        b = x.shape[0]
        t_arr = (jnp.zeros((b,), jnp.int32) if t is None
                 else jnp.asarray(t, jnp.int32))
        return CartPoleState(x=x, t=t_arr, key=jax.random.split(key, b))


JAX_ENVS = {"CartPole-v1": JaxCartPole}


def make_jax_env(name: str):
    if name not in JAX_ENVS:
        raise KeyError(f"no pure-JAX env {name!r}; available: "
                       f"{sorted(JAX_ENVS)}")
    return JAX_ENVS[name]
