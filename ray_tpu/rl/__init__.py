"""ray_tpu.rl: the RL stack (the reference's RLlib capability surface).

CPU EnvRunner actor fleets sample vectorized envs with jitted inference;
Learners apply jitted JAX updates (single host, mesh-sharded over TPU
chips, or a LearnerGroup of actors syncing host-side); algorithms — PPO,
IMPALA (V-trace, async), DQN (double-Q + prioritized replay), SAC — are
Tune Trainables.
"""

from ray_tpu.rl.algorithm import Algorithm  # noqa: F401
from ray_tpu.rl.algorithms import (  # noqa: F401
    A2C,
    A2CConfig,
    APPO,
    APPOConfig,
    ApexDDPG,
    ApexDDPGConfig,
    ApexDQN,
    ApexDQNConfig,
    ARS,
    ARSConfig,
    AlphaZero,
    AlphaZeroConfig,
    ConnectFour,
    LeelaChessZero,
    LeelaChessZeroConfig,
    MCTS,
    TicTacToe,
    BanditConfig,
    BanditLinTS,
    BanditLinUCB,
    CQL,
    CQLConfig,
    CRR,
    CRRConfig,
    DT,
    DTConfig,
    DreamerV3,
    DreamerV3Config,
    MADDPG,
    MADDPGConfig,
    MBMPO,
    MBMPOConfig,
    MAML,
    MAMLConfig,
    PointGoal,
    PG,
    PGConfig,
    ES,
    ESConfig,
    BC,
    BCConfig,
    DDPG,
    DDPGConfig,
    DQN,
    DQNConfig,
    IMPALA,
    IMPALAConfig,
    MARWIL,
    MARWILConfig,
    PPO,
    PPOConfig,
    QMIX,
    QMIXConfig,
    R2D2,
    R2D2Config,
    MaskedCartPole,
    SAC,
    SACConfig,
    SimpleQ,
    SimpleQConfig,
    RecSlateEnv,
    SlateQ,
    SlateQConfig,
    TD3,
    TD3Config,
)
from ray_tpu.rl.config import AlgorithmConfig  # noqa: F401
from ray_tpu.rl.rl_module import (  # noqa: F401
    Catalog,
    ModuleSpec,
    MultiAgentRLModule,
    RLModule,
    register_module_builder,
)
from ray_tpu.rl.connectors import (  # noqa: F401
    ClipObs,
    ClipReward,
    Connector,
    ConnectorPipeline,
    MeanStdFilter,
    build_connectors,
)
from ray_tpu.rl import ope  # noqa: F401
from ray_tpu.rl import pixel_env  # noqa: F401 — registers CatchPixels-v0
from ray_tpu.rl.pixel_env import (  # noqa: F401
    CatchPixels,
    FrameStack,
    PixelWrapper,
    gym_vector_env,
)
from ray_tpu.rl.policy_server import (  # noqa: F401
    ExternalEnvRunner,
    PolicyClient,
)
from ray_tpu.rl.multi_agent import (  # noqa: F401
    CoordinationGame,
    MultiAgentEnv,
    MultiAgentPPO,
    SpreadGame,
    register_multi_agent_env,
)
from ray_tpu.rl.env import (  # noqa: F401
    CartPole,
    EnvSpec,
    Pendulum,
    VectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rl.env_runner import EnvRunner, compute_gae  # noqa: F401
from ray_tpu.rl.learner import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rl.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
