"""Off-policy evaluation estimators: IS / WIS / DM / DR.

Reference analog: ``rllib/offline/estimators/`` —
``importance_sampling.py``, ``weighted_importance_sampling.py``,
``direct_method.py``, ``doubly_robust.py`` (step-wise DR per Jiang & Li
2016, the reference's cited formulation). Redesigned functional: estimators
are pure numpy over an episode list plus policy callables, with no coupling
to the sampling stack — offline batches from ``data`` readers or the replay
buffer both fit.

Episode format: dict with ``rewards`` [T], ``behavior_logp`` [T], and for
the target policy a per-episode ``target_logp`` [T] (precomputed by the
caller via its policy; keeps jax out of this module). DM/DR additionally
take ``q_values`` [T, A] and ``target_probs`` [T, A].
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def _cum_weights(ep: Dict, clip: float) -> np.ndarray:
    """ρ_{0:t}: cumulative importance weights, optionally clipped."""
    w = np.exp(np.asarray(ep["target_logp"], np.float64)
               - np.asarray(ep["behavior_logp"], np.float64))
    if clip:
        w = np.minimum(w, clip)
    return np.cumprod(w)


def _discounts(t: int, gamma: float) -> np.ndarray:
    return gamma ** np.arange(t)


def importance_sampling(episodes: Sequence[Dict], gamma: float = 1.0,
                        weight_clip: float = 0.0) -> Dict[str, float]:
    """Ordinary per-decision IS: V = E_i[ Σ_t γ^t ρ_{0:t} r_t ]."""
    v_b, v_t = [], []
    for ep in episodes:
        r = np.asarray(ep["rewards"], np.float64)
        g = _discounts(len(r), gamma)
        rho = _cum_weights(ep, weight_clip)
        v_b.append(float((g * r).sum()))
        v_t.append(float((g * rho * r).sum()))
    return {"v_behavior": float(np.mean(v_b)),
            "v_target": float(np.mean(v_t)),
            "v_gain": float(np.mean(v_t) / (np.mean(v_b) or 1.0))}


def weighted_importance_sampling(episodes: Sequence[Dict],
                                 gamma: float = 1.0,
                                 weight_clip: float = 0.0
                                 ) -> Dict[str, float]:
    """WIS: per-timestep self-normalized weights — biased, far lower
    variance (the reference's default go-to estimator)."""
    t_max = max(len(ep["rewards"]) for ep in episodes)
    n = len(episodes)
    rho = np.zeros((n, t_max), np.float64)
    rew = np.zeros((n, t_max), np.float64)
    alive = np.zeros((n, t_max), np.float64)
    for i, ep in enumerate(episodes):
        t = len(ep["rewards"])
        rho[i, :t] = _cum_weights(ep, weight_clip)
        rew[i, :t] = ep["rewards"]
        alive[i, :t] = 1.0
    # normalizer: mean cumulative weight among episodes still alive at t
    denom = (rho * alive).sum(0) / np.maximum(alive.sum(0), 1.0)
    denom = np.where(denom <= 0, 1.0, denom)
    g = _discounts(t_max, gamma)
    v_t = (g * (rho / denom) * rew).sum(1).mean()
    v_b = (g * rew).sum(1).mean()
    return {"v_behavior": float(v_b), "v_target": float(v_t),
            "v_gain": float(v_t / (v_b or 1.0))}


def direct_method(episodes: Sequence[Dict], gamma: float = 1.0
                  ) -> Dict[str, float]:
    """DM: V = E_i[ Σ_a π(a|s_0) Q(s_0, a) ] — all model, no correction."""
    v_t, v_b = [], []
    for ep in episodes:
        q0 = np.asarray(ep["q_values"], np.float64)[0]
        p0 = np.asarray(ep["target_probs"], np.float64)[0]
        v_t.append(float((p0 * q0).sum()))
        r = np.asarray(ep["rewards"], np.float64)
        v_b.append(float((_discounts(len(r), gamma) * r).sum()))
    return {"v_behavior": float(np.mean(v_b)),
            "v_target": float(np.mean(v_t)),
            "v_gain": float(np.mean(v_t) / (np.mean(v_b) or 1.0))}


def doubly_robust(episodes: Sequence[Dict], gamma: float = 1.0,
                  weight_clip: float = 0.0) -> Dict[str, float]:
    """Step-wise DR (Jiang & Li 2016):
    v_t = V̂(s_t) + ρ_t (r_t + γ v_{t+1} - Q̂(s_t, a_t)),  backwards in t.

    Unbiased if EITHER the Q-model or the importance weights are right —
    the property the test suite checks with a deliberately wrong model.
    """
    v_t, v_b = [], []
    for ep in episodes:
        r = np.asarray(ep["rewards"], np.float64)
        q = np.asarray(ep["q_values"], np.float64)        # [T, A]
        probs = np.asarray(ep["target_probs"], np.float64)  # [T, A]
        acts = np.asarray(ep["actions"], np.int64)
        w = np.exp(np.asarray(ep["target_logp"], np.float64)
                   - np.asarray(ep["behavior_logp"], np.float64))
        if weight_clip:
            w = np.minimum(w, weight_clip)
        v_hat = (probs * q).sum(1)                         # V̂(s_t)
        q_taken = q[np.arange(len(r)), acts]               # Q̂(s_t, a_t)
        v = 0.0
        for t in range(len(r) - 1, -1, -1):
            v = v_hat[t] + w[t] * (r[t] + gamma * v - q_taken[t])
        v_t.append(float(v))
        v_b.append(float((_discounts(len(r), gamma) * r).sum()))
    return {"v_behavior": float(np.mean(v_b)),
            "v_target": float(np.mean(v_t)),
            "v_gain": float(np.mean(v_t) / (np.mean(v_b) or 1.0))}


ESTIMATORS = {
    "is": importance_sampling,
    "wis": weighted_importance_sampling,
    "dm": direct_method,
    "dr": doubly_robust,
}


def estimate(method: str, episodes: Sequence[Dict], **kwargs
             ) -> Dict[str, float]:
    if method not in ESTIMATORS:
        raise ValueError(f"unknown estimator {method!r}; "
                         f"have {sorted(ESTIMATORS)}")
    return ESTIMATORS[method](episodes, **kwargs)


def episodes_from_batch(batch: Dict[str, np.ndarray],
                        num_envs: int = 1) -> List[Dict]:
    """Split a flat columnar batch (with ``dones``) into episode dicts —
    the bridge from offline datasets / sample batches to the estimators.

    ``EnvRunner.sample`` flattens its ``[T, N]`` buffers time-major
    (row ``t*N + n`` is env ``n`` at step ``t``), so batches collected with
    ``num_envs_per_runner > 1`` interleave environments; pass that count as
    ``num_envs`` so rows are first de-interleaved per env — splitting the
    raw interleaved rows on ``dones`` would stitch timesteps of unrelated
    trajectories into one "episode" and silently corrupt the estimates.
    """
    dones = np.asarray(batch["dones"]).astype(bool)
    if dones.size == 0:
        return []
    if num_envs > 1:
        if len(dones) % num_envs:
            raise ValueError(
                f"batch length {len(dones)} not divisible by "
                f"num_envs={num_envs}")
        episodes = []
        for n in range(num_envs):
            episodes.extend(episodes_from_batch(
                {k: np.asarray(v)[n::num_envs] for k, v in batch.items()}))
        return episodes
    bounds = np.flatnonzero(dones) + 1
    episodes = []
    start = 0
    for end in list(bounds) + ([len(dones)] if not dones[-1] else []):
        if end <= start:
            continue
        episodes.append({k: np.asarray(v)[start:end]
                         for k, v in batch.items()})
        start = end
    return episodes
