"""The RLHF pipeline driver: placed roles, interleaved phases, streamed
weight sync.

One iteration is four phases over four PLACED role actors (one
placement-group bundle each, ``train/worker_group.RoleGroup``):

  generate   prompts decode on the generator's ContinuousEngine slots
             (``models/serving.py`` — mid-flight admission, K-fused
             ticks; the same engine the serve path runs)
  score      the reward model scores full sequences; the frozen
             reference model logprobs the generated spans (both fire in
             parallel — they are independent reads)
  update     the policy learner runs a PPO-style clipped update on the
             sampled sequences (sequence-level advantage = reward −
             kl_coeff · KL(policy‖reference), batch-normalized)
  sync       fresh learner weights ship to the generator over
             ``cluster/stream.py`` oid frames (``collective.
             ship_params`` — plasma spill above the inline threshold,
             pull fallback on a broken channel) and land through the
             engine's drain-barrier ``load_params`` swap, so in-flight
             streams finish token-exact under the old weights and the
             next generate phase decodes the new ones

Every phase call runs under ONE ambient trace span, so ``rt trace
<pipeline.trace_id>`` shows the whole story: role creation (placement),
then each iteration's generate/score/update/sync hops.

Each role additionally stamps its phase interval ACTOR-SIDE (wall-clock
``t0``/``t1`` inside the method, returned with the result) and the
driver joins those intervals into one iteration record on the pipeline
flight recorder (``util/pipeline_recorder.py``): per-role busy/idle and
the strict-phase bubble fraction, the orchestration tax (driver wall
minus actor wall per phase), the learner's monotonic weights-version vs
the version each generate batch decoded under (measured staleness), and
the joined ship→fetch→barrier→swap transfer receipt. Read it live via
``rt rlhf stats`` / the dashboard RLHF tab, postmortem off the ``@rlhf/``
GCS snapshot.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.util import metrics as M

# ---------------------------------------------------------------------------
# metrics (lazy — the registry must not be touched at import time)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Dict[str, Any] = {}  # rt: guarded-by(_metrics_lock)

_PHASE_BUCKETS = (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 120.0)


def _metric(key: str, factory: Callable[[], Any]) -> Any:
    with _metrics_lock:
        m = _metrics.get(key)
        if m is None:
            m = _metrics[key] = factory()
        return m


def iterations_total() -> "M.Counter":
    return _metric("iters", lambda: M.get_or_create(
        M.Counter, "rt_rlhf_iterations_total",
        "RLHF pipeline iterations completed (generate -> score -> "
        "update -> weight-sync)"))


def tokens_generated_total() -> "M.Counter":
    return _metric("toks", lambda: M.get_or_create(
        M.Counter, "rt_rlhf_tokens_generated_total",
        "Tokens decoded by the RLHF generate phase on the continuous "
        "engine"))


def reward_mean_gauge() -> "M.Gauge":
    return _metric("reward", lambda: M.get_or_create(
        M.Gauge, "rt_rlhf_reward_mean",
        "Mean reward-model score of the last RLHF iteration's batch"))


def phase_seconds() -> "M.Histogram":
    return _metric("phase", lambda: M.get_or_create(
        M.Histogram, "rt_rlhf_phase_seconds",
        "Wall seconds per RLHF pipeline phase, phase= (generate / "
        "score / update / ship / sync / sync_swap ...) x side= (driver "
        "= driver-observed, actor = stamped inside the role's method; "
        "the gap is the orchestration tax)",
        tag_keys=("phase", "side"), boundaries=_PHASE_BUCKETS))


def weight_sync_bytes_total() -> "M.Counter":
    return _metric("sync_bytes", lambda: M.get_or_create(
        M.Counter, "rt_rlhf_weight_sync_bytes_total",
        "Parameter bytes shipped learner -> generation engine per "
        "weight sync, transport= (push / fallback / pull)",
        tag_keys=("transport",)))


def weight_sync_seconds() -> "M.Histogram":
    return _metric("sync_s", lambda: M.get_or_create(
        M.Histogram, "rt_rlhf_weight_sync_seconds",
        "Wall seconds of one weight sync (ship + fetch + drain-barrier "
        "engine swap)", boundaries=_PHASE_BUCKETS))


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RLHFConfig:
    preset: str = "debug"
    num_prompts: int = 4          # sequences per iteration
    prompt_len: int = 8
    max_new_tokens: int = 16
    max_slots: int = 4            # generation engine decode slots
    decode_stride: int = 4
    lr: float = 1e-4
    kl_coeff: float = 0.1
    clip_param: float = 0.2
    num_epochs: int = 2
    seed: int = 0
    cpus_per_role: float = 1.0

    @property
    def max_len(self) -> int:
        return self.prompt_len + self.max_new_tokens + 2


# ---------------------------------------------------------------------------
# role actors
# ---------------------------------------------------------------------------


class RLHFLearner:
    """The policy owner: holds the ONLY writable copy of the policy and
    runs the PPO-style sequence update; ships weights by ticket."""

    def __init__(self, preset: str, seed: int, lr: float, kl_coeff: float,
                 clip_param: float, num_epochs: int):
        import functools

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import llama
        from ray_tpu.rl.rlhf import models as rlhf_models

        self.cfg = llama.PRESETS[preset]
        self._params = llama.init_params(jax.random.key(seed), self.cfg)
        self._kl_coeff = kl_coeff
        self._num_epochs = num_epochs
        self._opt = optax.adam(lr)
        self._opt_state = self._opt.init(self._params)
        self._updates = 0
        cfg, opt = self.cfg, self._opt

        @functools.partial(jax.jit, static_argnums=(5,))
        def update_step(params, opt_state, tokens, old_logp, adv,
                        prompt_len):
            def loss_fn(p):
                logp = rlhf_models.seq_logprob_body(
                    p, tokens, prompt_len, cfg)
                ratio = jnp.exp(logp - old_logp)
                a = adv[:, None]
                surr = jnp.minimum(
                    ratio * a,
                    jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * a)
                return -jnp.mean(surr), jnp.mean(ratio)

            (loss, ratio_mean), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, ratio_mean

        self._update_step = update_step

    def ping(self) -> str:
        return "learner"

    def update(self, sequences, rewards, ref_logps,
               prompt_len: int) -> Dict[str, Any]:
        """One PPO-style update on the sampled sequences; returns
        iteration metrics (plus the actor-side phase stamp and the new
        monotonic weights-version)."""
        import jax.numpy as jnp

        from ray_tpu.rl.rlhf import models as rlhf_models

        t0 = time.time()
        tokens = jnp.asarray(np.asarray(sequences, np.int32))
        rewards = jnp.asarray(np.asarray(rewards, np.float32))
        ref_logps = jnp.asarray(np.asarray(ref_logps, np.float32))
        old_logp = rlhf_models.sequence_logprobs(
            self._params, tokens, prompt_len, self.cfg)
        # sequence-level objective: reward-model score minus the KL
        # anchor to the reference policy, normalized across the batch
        kl_seq = jnp.sum(old_logp - ref_logps, axis=-1)
        adj = rewards - self._kl_coeff * kl_seq
        adv = (adj - adj.mean()) / (adj.std() + 1e-6)
        loss = ratio = 0.0
        for _ in range(self._num_epochs):
            (self._params, self._opt_state, loss,
             ratio) = self._update_step(
                self._params, self._opt_state, tokens,
                old_logp, adv, prompt_len)
        self._updates += 1
        # force the async-dispatched update chain to completion BEFORE
        # stamping t1: the float() conversions block on the final
        # epoch's computation, and stamping first would hide the real
        # compute from the actor-side interval (the driver would then
        # book it as orchestration tax)
        loss_f, ratio_f = float(loss), float(ratio)
        kl_f = float(jnp.mean(kl_seq))
        reward_f = float(jnp.mean(rewards))
        t1 = time.time()
        # self._updates IS the monotonic weights-version: each update
        # produces a new version, and the ship ticket carries it so the
        # generator can stamp which version every batch decoded under
        return {"loss": loss_f, "ratio_mean": ratio_f,
                "kl_mean": kl_f, "reward_mean": reward_f,
                "updates": self._updates, "version": self._updates,
                "t0": t0, "t1": t1, "wall_s": round(t1 - t0, 6)}

    def ship_weights(self) -> Dict[str, Any]:
        """Ship the current policy: returns the stream ticket the
        generator redeems (tensor bytes travel as oid frames, not
        through this actor call's reply). The ticket additionally
        carries the weights-version and the actor-side ship stamp —
        ``fetch_params`` reads only address/sid, extra keys ride free."""
        from ray_tpu import collective

        t0 = time.time()
        ticket = collective.ship_params(self._params)
        t1 = time.time()
        ticket["version"] = self._updates
        ticket["t0"] = t0
        ticket["t1"] = t1
        ticket["wall_s"] = round(t1 - t0, 6)
        return ticket

    def shipment_receipt(self, sid: str) -> Optional[Dict[str, Any]]:
        """Producer-side pump receipt for one shipment (first/last
        ``take`` wall) — the driver joins it with the consumer's fetch
        wall into the transfer receipt."""
        from ray_tpu import collective

        return collective.shipment_receipt(sid)

    def cancel_shipment(self, ticket: Dict[str, Any]) -> None:
        """Drop an unredeemed shipment (the pipeline calls this when
        the generator's sync fails — otherwise each failed round
        strands a full parameter copy in this process's registry)."""
        from ray_tpu import collective

        collective.cancel_shipment(ticket)

    def get_params(self):
        return self._params


class RLHFReference:
    """Frozen copy of the initial policy: the KL anchor."""

    def __init__(self, preset: str, seed: int):
        import jax

        from ray_tpu.models import llama

        self.cfg = llama.PRESETS[preset]
        # seed matches the learner's init — the reference IS the initial
        # policy, per the standard RLHF recipe
        self._params = llama.init_params(jax.random.key(seed), self.cfg)

    def ping(self) -> str:
        return "reference"

    def logprobs(self, sequences, prompt_len: int) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ray_tpu.rl.rlhf import models as rlhf_models

        t0 = time.time()
        tokens = jnp.asarray(np.asarray(sequences, np.int32))
        out = np.asarray(rlhf_models.sequence_logprobs(
            self._params, tokens, prompt_len, self.cfg))
        t1 = time.time()
        return {"logprobs": out, "t0": t0, "t1": t1,
                "wall_s": round(t1 - t0, 6)}


class RLHFReward:
    """The preference model: scalar score per full sequence."""

    def __init__(self, preset: str, seed: int):
        import jax

        from ray_tpu.models import llama
        from ray_tpu.rl.rlhf import models as rlhf_models

        self.cfg = llama.PRESETS[preset]
        self._params = rlhf_models.init_reward_params(
            jax.random.key(seed), self.cfg)

    def ping(self) -> str:
        return "reward"

    def score(self, sequences) -> Dict[str, Any]:
        import jax.numpy as jnp

        from ray_tpu.rl.rlhf import models as rlhf_models

        t0 = time.time()
        tokens = jnp.asarray(np.asarray(sequences, np.int32))
        out = np.asarray(rlhf_models.reward_score(
            self._params, tokens, self.cfg))
        t1 = time.time()
        return {"scores": out, "t0": t0, "t1": t1,
                "wall_s": round(t1 - t0, 6)}


class RLHFGenerator:
    """The generation engine role: one ContinuousEngine (the serve
    path's continuous batcher) decoding the policy; weight syncs land
    through the drain-barrier swap."""

    def __init__(self, preset: str, seed: int, max_slots: int,
                 max_len: int, decode_stride: int):
        import jax

        from ray_tpu.models import llama
        from ray_tpu.models.serving import ContinuousEngine

        self.cfg = llama.PRESETS[preset]
        # same seed as the learner: generation starts on the SAME
        # initial policy the learner will update
        params = llama.init_params(jax.random.key(seed), self.cfg)
        self.engine = ContinuousEngine(
            params, self.cfg, max_slots=max_slots, max_len=max_len,
            decode_stride=decode_stride)
        # version of the weights currently decoding (0 = the seed init;
        # each sync stamps the learner version its ticket carried) — an
        # actor restart resets this to 0, which is exactly right: the
        # rebuilt engine decodes the seed weights again
        self._weights_version = 0

    def ping(self) -> str:
        return "generator"

    def generate(self, prompts, max_new_tokens: int) -> Dict[str, Any]:
        """Decode every prompt through the engine's slots (mid-flight
        admission; the engine queues past the slot budget). Returns
        full sequences (prompt + generation), engine counters, and the
        weights-version the batch decoded under (a mid-generate swap
        shows as start != end)."""
        t0 = time.time()
        tp0 = time.perf_counter()
        version_start = self._weights_version
        queues = [self.engine.submit_stream(
            np.asarray(p, np.int32), max_new_tokens) for p in prompts]
        seqs = []
        for p, q in zip(prompts, queues):
            toks = [t for t in iter(q.get, None)]
            seqs.append(list(p) + toks)
        dt = time.perf_counter() - tp0
        t1 = time.time()
        n_new = sum(len(s) - len(p) for s, p in zip(seqs, prompts))
        return {"sequences": np.asarray(seqs, np.int32),
                "tokens_generated": n_new,
                "tok_s": round(n_new / max(dt, 1e-9), 1),
                "wall_s": round(dt, 4),
                "t0": t0, "t1": t1,
                "weights_version_start": version_start,
                "weights_version": self._weights_version,
                "engine": self.engine.stats()}

    def sync_weights(self, ticket: Dict[str, Any]) -> Dict[str, Any]:
        """Redeem the learner's ticket: fetch the shipped weights over
        the stream plane, swap them in behind the drain barrier. Stamps
        the actor-side sync interval and the version now decoding."""
        from ray_tpu import collective

        t0 = time.time()
        tp0 = time.perf_counter()
        params, info = collective.fetch_params(ticket)
        swap = self.engine.load_params(params)
        self._weights_version = int(
            ticket.get("version", self._weights_version + 1))
        info.update(swap)
        info["version"] = self._weights_version
        info["sync_s"] = round(time.perf_counter() - tp0, 4)
        info["t0"] = t0
        info["t1"] = time.time()
        return info

    def engine_stats(self) -> Dict[str, Any]:
        return self.engine.stats()


# ---------------------------------------------------------------------------
# the pipeline driver
# ---------------------------------------------------------------------------


class RLHFPipeline:
    """Places the four roles and interleaves generate/score/update/sync.

    Requires an initialized ray_tpu session. ``trace_id`` identifies the
    pipeline's span tree (`rt trace <trace_id>` shows role placement and
    every phase hop).
    """

    def __init__(self, cfg: Optional[RLHFConfig] = None, **overrides):
        from ray_tpu.train.worker_group import RoleGroup
        from ray_tpu.util import pipeline_recorder as _prec
        from ray_tpu.util import tracing

        self.cfg = cfg or RLHFConfig(**overrides)
        c = self.cfg
        self._rng = np.random.default_rng(c.seed)
        self._lock = threading.Lock()
        self._iterations = 0       # rt: guarded-by(_lock)
        self._tokens_generated = 0  # rt: guarded-by(_lock)
        self._sync_bytes = 0       # rt: guarded-by(_lock)
        self._last: Dict[str, Any] = {}  # rt: guarded-by(_lock)
        # the learner's weights-version as of the LAST completed update
        # (what the learner held while this iteration's batch decoded) —
        # staleness = this minus the version the generator decoded under
        self._learner_version = 0  # rt: guarded-by(_lock)
        # ONE ambient span for the pipeline's lifetime: role creation
        # and every phase call become children of this synthetic root,
        # so the whole story lands under one trace id
        self._trace_ctx = {"trace_id": uuid.uuid4().hex,
                           "span_id": uuid.uuid4().hex[:16]}
        self.trace_id = self._trace_ctx["trace_id"]
        self.group = RoleGroup(f"rlhf-{self.trace_id[:8]}",
                               strategy="PACK")
        self.group.add_role(
            "learner", RLHFLearner, c.preset, c.seed, c.lr, c.kl_coeff,
            c.clip_param, c.num_epochs, num_cpus=c.cpus_per_role)
        self.group.add_role("reference", RLHFReference, c.preset, c.seed,
                            num_cpus=c.cpus_per_role)
        self.group.add_role("reward", RLHFReward, c.preset, c.seed + 1,
                            num_cpus=c.cpus_per_role)
        # the generator survives one chaos/crash restart: a killed
        # engine rebuilds on the seed weights (weights-version 0) and
        # the next iteration's staleness stamp shows the regression
        self.group.add_role(
            "generator", RLHFGenerator, c.preset, c.seed, c.max_slots,
            c.max_len, c.decode_stride, num_cpus=c.cpus_per_role,
            options={"max_restarts": 1})
        self.recorder = _prec.PipelineRecorder(
            f"rlhf-{self.trace_id[:8]}")
        token = tracing.activate(self._trace_ctx)
        try:
            self.group.start()
        except BaseException:
            tracing.deactivate(token)
            raise
        tracing.deactivate(token)

    # -- phases -----------------------------------------------------------

    def _sample_prompts(self) -> List[List[int]]:
        from ray_tpu.models import llama

        c = self.cfg
        vocab = llama.PRESETS[c.preset].vocab_size
        return [[int(t) for t in
                 self._rng.integers(1, vocab, size=c.prompt_len)]
                for _ in range(c.num_prompts)]

    def run_iteration(self) -> Dict[str, Any]:
        """One generate -> score -> update -> sync round; returns the
        iteration's metrics (also pushed onto the ``rt_rlhf_*`` series
        and joined onto the pipeline flight recorder). A round that dies
        mid-phase stamps the interrupted phase on the recorder before
        re-raising, so the postmortem snapshot names where it stopped.
        """
        import ray_tpu
        from ray_tpu.util import tracing

        c = self.cfg
        g = self.group
        with self._lock:
            learner_version = self._learner_version
        iter_t0 = time.time()
        iter_p0 = time.perf_counter()
        cur_phase = "generate"
        token = tracing.activate(self._trace_ctx)
        try:
            phases: Dict[str, float] = {}
            t0 = time.perf_counter()
            gen = ray_tpu.get(g["generator"].generate.remote(
                self._sample_prompts(), c.max_new_tokens))
            phases["generate"] = time.perf_counter() - t0
            seqs = gen["sequences"]

            cur_phase = "score"
            t0 = time.perf_counter()
            # reward + reference fire in parallel: independent reads
            reward_ref = g["reward"].score.remote(seqs)
            ref_ref = g["reference"].logprobs.remote(seqs, c.prompt_len)
            reward_out, ref_out = ray_tpu.get([reward_ref, ref_ref])
            rewards = reward_out["scores"]
            ref_logps = ref_out["logprobs"]
            phases["score"] = time.perf_counter() - t0

            cur_phase = "update"
            t0 = time.perf_counter()
            update = ray_tpu.get(g["learner"].update.remote(
                seqs, rewards, ref_logps, c.prompt_len))
            phases["update"] = time.perf_counter() - t0

            cur_phase = "ship"
            t0 = time.perf_counter()
            ticket = ray_tpu.get(g["learner"].ship_weights.remote())
            d_ship = time.perf_counter() - t0
            cur_phase = "sync_swap"
            try:
                sync = ray_tpu.get(
                    g["generator"].sync_weights.remote(ticket))
            except BaseException:
                # the shipment was never redeemed: drop it, or every
                # failed round strands a full parameter copy in the
                # learner's source registry
                try:
                    ray_tpu.get(
                        g["learner"].cancel_shipment.remote(ticket))
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
                raise
            d_swap = time.perf_counter() - t0 - d_ship
            phases["sync"] = time.perf_counter() - t0
            # the iteration's WORK ends here: the pump-receipt read
            # below is recorder telemetry, not pipeline dataflow, so it
            # stays out of the wall the coverage ratio divides by
            iter_p1 = time.perf_counter()
            # producer-side pump receipt, read AFTER the consumer
            # redeemed the ticket (the receipt registry outlives the
            # shipment's deregistration)
            try:
                pump = ray_tpu.get(g["learner"].shipment_receipt.remote(
                    ticket["sid"]))
            except Exception:  # noqa: BLE001 — receipt is telemetry
                pump = None
        except BaseException as exc:
            try:
                self.recorder.record_interrupt(
                    phase=cur_phase, t=time.time(), error=repr(exc))
            except Exception:  # noqa: BLE001 — recorder never masks
                pass           # the real failure
            raise
        finally:
            tracing.deactivate(token)
        iter_wall = iter_p1 - iter_p0

        # join the actor-side stamps (all roles share the host clock —
        # PACK placement) into the recorder's iteration record
        intervals = [
            {"role": "generator", "phase": "generate",
             "t0": gen["t0"], "t1": gen["t1"]},
            {"role": "reward", "phase": "score_reward",
             "t0": reward_out["t0"], "t1": reward_out["t1"]},
            {"role": "reference", "phase": "score_ref",
             "t0": ref_out["t0"], "t1": ref_out["t1"]},
            {"role": "learner", "phase": "update",
             "t0": update["t0"], "t1": update["t1"]},
            {"role": "learner", "phase": "ship",
             "t0": ticket["t0"], "t1": ticket["t1"]},
            {"role": "generator", "phase": "sync_swap",
             "t0": sync["t0"], "t1": sync["t1"]},
        ]
        driver_s = {"generate": phases["generate"],
                    "score": phases["score"],
                    "update": phases["update"],
                    "ship": d_ship, "sync_swap": d_swap}
        receipt = {"version": int(ticket.get("version", 0)),
                   "nbytes": int(sync["nbytes"]),
                   "n_leaves": int(sync.get("n_leaves", 0)),
                   "oid_leaves": int(sync.get("oid_leaves", 0)),
                   "inline_leaves": int(sync.get("inline_leaves", 0)),
                   "transport": sync["transport"],
                   "rpcs": int(sync.get("rpcs", 0)),
                   "ship_wall_s": ticket.get("wall_s", 0.0),
                   "fetch_wall_s": sync.get("fetch_wall_s", 0.0),
                   "barrier_drain_s": sync["drain_s"],
                   "swap_apply_s": sync.get("apply_s", 0.0)}
        if pump and "pump_wall_s" in pump:
            receipt["pump_wall_s"] = pump["pump_wall_s"]
            receipt["frames_taken"] = int(pump.get("frames_taken", 0))
        decoded_version = int(gen.get("weights_version", 0))
        staleness = max(0, learner_version - decoded_version)

        result = {
            "iteration": None,  # filled under the lock below
            "tokens_generated": int(gen["tokens_generated"]),
            "generate_tok_s": gen["tok_s"],
            "reward_mean": update["reward_mean"],
            "kl_mean": update["kl_mean"],
            "loss": update["loss"],
            "sync_transport": sync["transport"],
            "sync_bytes": int(sync["nbytes"]),
            "sync_oid_leaves": int(sync.get("oid_leaves", 0)),
            "sync_s": sync["sync_s"],
            "swap_drain_s": sync["drain_s"],
            "phases_s": {k: round(v, 4) for k, v in phases.items()},
            "phases_actor_s": {iv["phase"]: round(
                max(0.0, iv["t1"] - iv["t0"]), 4) for iv in intervals},
            "weights_version": int(update.get("version", 0)),
            "decoded_version": decoded_version,
            "staleness": staleness,
            "receipt": receipt,
            "trace_id": self.trace_id,
        }
        with self._lock:
            self._iterations += 1
            self._tokens_generated += result["tokens_generated"]
            self._sync_bytes += result["sync_bytes"]
            self._learner_version = result["weights_version"]
            result["iteration"] = self._iterations
            self._last = result
        try:
            derived = self.recorder.record_iteration(
                iteration=result["iteration"], t0=iter_t0,
                wall_s=iter_wall, intervals=intervals,
                driver_s=driver_s,
                tokens=result["tokens_generated"],
                learner_version=learner_version,
                decoded_version=decoded_version, receipt=receipt)
            result["bubble_fraction"] = derived.get("bubble_fraction")
            result["coverage"] = derived.get("coverage")
            result["tax_s"] = derived.get("tax_s")
            if derived.get("restart_gap_s") is not None:
                result["restart_gap_s"] = derived["restart_gap_s"]
        except Exception:  # noqa: BLE001 — recorder never fails a round
            pass
        try:
            iterations_total().inc()
            tokens_generated_total().inc(result["tokens_generated"])
            reward_mean_gauge().set(result["reward_mean"])
            for phase, secs in phases.items():
                phase_seconds().observe(secs, tags={"phase": phase,
                                                    "side": "driver"})
            for iv in intervals:
                phase_seconds().observe(
                    max(0.0, iv["t1"] - iv["t0"]),
                    tags={"phase": iv["phase"], "side": "actor"})
            weight_sync_bytes_total().inc(
                result["sync_bytes"],
                {"transport": result["sync_transport"]})
            weight_sync_seconds().observe(result["sync_s"])
        except Exception:  # noqa: BLE001 — telemetry never fails a round
            pass
        return result

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"iterations": self._iterations,
                   "tokens_generated": self._tokens_generated,
                   "sync_bytes_total": self._sync_bytes,
                   "trace_id": self.trace_id,
                   "placement": self.group.describe(),
                   "last": dict(self._last)}
        try:
            out["recorder"] = self.recorder.summary()
        except Exception:  # noqa: BLE001 — stats never fail on telemetry
            pass
        return out

    def shutdown(self) -> None:
        try:
            self.recorder.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        self.group.shutdown()
