"""TPU-native RLHF: policy/reference/reward placement, ContinuousEngine
generation, PPO-style sequence updates, streamed weight sync.

The end-to-end pipeline ROADMAP item 5 names (arxiv 2312.11819 adaptive
placement + interleaved generate/train; MindSpeed RL 2507.19017):

- ``pipeline.RLHFPipeline`` — the driver: places the policy learner,
  reference model, reward model and generation engine as role actors
  (one per placement-group bundle, ``train/worker_group.RoleGroup``),
  then interleaves generate → score → update → weight-sync phases.
- ``models`` — the llama-backed reward model and sequence-logprob
  utilities the roles share.

The generate phase runs on ``models/serving.ContinuousEngine`` slots;
fresh learner weights travel over ``cluster/stream.py`` oid frames via
``collective.ship_params`` and land through the engine's drain-barrier
``load_params`` swap.
"""

from ray_tpu.rl.rlhf.models import (  # noqa: F401
    init_reward_params,
    reward_score,
    sequence_logprobs,
)
from ray_tpu.rl.rlhf.pipeline import (  # noqa: F401
    RLHFConfig,
    RLHFPipeline,
)
