"""RLHF model pieces over the llama trunk: reward scoring + sequence
logprobs.

The three RLHF roles share one architecture family (``models/llama.py``
presets) so placement is a pure resource decision:

- the REWARD model is a llama trunk with a scalar head read at the last
  position (the standard preference-model shape);
- the REFERENCE model is a frozen copy of the initial policy — its
  per-token logprobs anchor the KL penalty;
- the POLICY is the llama LM itself (the generation engine decodes it,
  the learner updates it).

Compiled entry points are ``lru_cache``-keyed by (config, shape) — the
same one-program-per-shape idiom as ``models/serving.py`` — so repeated
pipeline iterations at fixed batch shapes pay zero retrace.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.models import llama

Params = Dict[str, Any]


def init_reward_params(rng: jax.Array, cfg: llama.LlamaConfig) -> Params:
    """Llama trunk + scalar reward head (read at the final position)."""
    k_lm, k_head = jax.random.split(rng)
    head = (jax.random.normal(k_head, (cfg.d_model, 1), jnp.float32)
            * (1.0 / jnp.sqrt(cfg.d_model))).astype(cfg.param_dtype)
    return {"lm": llama.init_params(k_lm, cfg), "head": head}


@functools.lru_cache(maxsize=32)
def _compiled_reward(cfg, b: int, s: int):
    @jax.jit
    def run(rm: Params, tokens: jax.Array) -> jax.Array:
        hidden, _ = llama.forward_hidden(rm["lm"], tokens, cfg)
        # scalar score from the last position's hidden state
        return (hidden[:, -1, :] @ rm["head"].astype(hidden.dtype)
                ).astype(jnp.float32)[:, 0]

    return run


def reward_score(rm: Params, tokens: jax.Array,
                 cfg: llama.LlamaConfig) -> jax.Array:
    """tokens [B, S] -> reward [B] (fp32)."""
    b, s = tokens.shape
    return _compiled_reward(cfg, b, s)(rm, tokens)


def seq_logprob_body(params: Params, tokens: jax.Array, prompt_len: int,
                     cfg: llama.LlamaConfig) -> jax.Array:
    """The traceable core of :func:`sequence_logprobs` (``prompt_len``
    must be a static python int) — the learner inlines this inside its
    jitted update so the logprob forward fuses into the loss trace."""
    logits = llama.forward(params, tokens[:, :-1], cfg)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    # position i of logits predicts token i+1: the generated span
    # tokens[prompt_len:] is scored by logits[prompt_len-1:]
    targets = tokens[:, prompt_len:]
    preds = logp_all[:, prompt_len - 1:, :]
    return jnp.take_along_axis(preds, targets[..., None], axis=-1)[..., 0]


@functools.lru_cache(maxsize=32)
def _compiled_seq_logprobs(cfg, b: int, s: int, prompt_len: int):
    @jax.jit
    def run(params: Params, tokens: jax.Array) -> jax.Array:
        return seq_logprob_body(params, tokens, prompt_len, cfg)

    return run


def sequence_logprobs(params: Params, tokens: jax.Array, prompt_len: int,
                      cfg: llama.LlamaConfig) -> jax.Array:
    """Per-token logprob of the GENERATED span under ``params``.

    tokens [B, S] (prompt + generation, S > prompt_len) -> [B, S -
    prompt_len] logprobs of tokens[:, prompt_len:] given their prefixes.
    """
    b, s = tokens.shape
    if prompt_len < 1 or prompt_len >= s:
        raise ValueError(f"prompt_len {prompt_len} out of range for "
                         f"sequence length {s}")
    return _compiled_seq_logprobs(cfg, b, s, prompt_len)(params, tokens)
