"""Algorithm: the top-level RL driver, runnable standalone or under Tune.

Reference analog: ``rllib/algorithms/algorithm.py:191`` — ``Algorithm`` is
a Tune ``Trainable`` whose ``step()`` delegates to the per-algorithm
``training_step()``. ``AlgorithmConfig.build()`` produces one directly;
``Tuner(PPO, param_space={...})`` runs it as trials with flat-dict config
overrides.
"""

from __future__ import annotations

import logging
import pickle
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger("ray_tpu.rl")

import ray_tpu
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import EnvSpec, make_env
from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.tune.trainable import Trainable


class Algorithm(Trainable):
    explore_mode = "stochastic"  # DQN overrides with "epsilon_greedy"
    need_env_runners = True      # offline algorithms (BC/MARWIL) opt out

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(algo_class=cls)

    # ---- Trainable API ----

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = self.get_default_config().update_from_dict(config)
        cfg = self.config
        if cfg.env.startswith("external://"):
            # external-env serving (rl/policy_server.py): the runner is an
            # HTTP policy server; the spec must be declared up front since
            # no env exists to probe (reference: policy_server_input needs
            # the space config too)
            from ray_tpu.rl.policy_server import ExternalEnvRunner

            spec_kwargs = cfg.env_config.get("spec")
            if not spec_kwargs:
                raise ValueError(
                    'external envs need env_config={"spec": {...EnvSpec '
                    'fields...}}')
            if cfg.connectors:
                raise ValueError(
                    "connectors are not applied by external-env runners "
                    "(the external simulator owns preprocessing); drop "
                    "config.connectors or filter client-side")
            self.spec = EnvSpec(**spec_kwargs)
            port = int(cfg.env.split("://", 1)[1] or 0)
            n_runners = max(1, cfg.num_env_runners) \
                if self.need_env_runners else 0
            self.runners = [
                ExternalEnvRunner.options(
                    num_cpus=cfg.num_cpus_per_runner,
                    runtime_env=cfg.runner_runtime_env).remote(
                    port + i if port else 0, dict(spec_kwargs),
                    cfg.rollout_fragment_length, cfg.num_envs_per_runner,
                    cfg.gamma, cfg.lambda_, seed=cfg.seed + 1000 * i)
                for i in range(n_runners)
            ]
            # bind now so callers can fetch ports before training starts
            self.server_ports = ray_tpu.get(
                [r.ready.remote() for r in self.runners])
        else:
            # probe the env spec without an actor round-trip
            self.spec = make_env(cfg.env, 1, cfg.env_config).spec
            n_runners = max(1, cfg.num_env_runners) \
                if self.need_env_runners else 0
            restarts = (cfg.max_env_runner_restarts
                        if cfg.restart_failed_env_runners else 0)
            self.runners = [
                EnvRunner.options(num_cpus=cfg.num_cpus_per_runner,
                                  runtime_env=cfg.runner_runtime_env,
                                  max_restarts=restarts).remote(
                    cfg.env, cfg.num_envs_per_runner,
                    cfg.rollout_fragment_length, cfg.gamma, cfg.lambda_,
                    seed=cfg.seed + 1000 * i, env_config=cfg.env_config,
                    explore=self.explore_mode, connectors=cfg.connectors)
                for i in range(n_runners)
            ]
        # driver-side pipeline skeleton: holds/merges the global connector
        # state the runner fleet syncs through (reference: filter deltas
        # flushed to the driver and re-broadcast each iteration)
        from ray_tpu.rl.connectors import build_connectors

        self._conn_pipeline = (build_connectors(cfg.connectors,
                                                self.spec.obs_dims[-1])
                               if n_runners else None)
        self._connector_state = None
        self._env_steps_total = 0
        self._return_window: List[float] = []
        self.build_learner()

    def build_learner(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        result.setdefault("env_steps_total", self._env_steps_total)
        return result

    # ---- helpers ----

    def init_policy_params(self):
        """Initial policy/value param pytree: routed through the RLModule
        Catalog when ``config.module_spec`` is set (custom encoder or
        activation), else the default ``models.init_policy`` network."""
        import jax

        from ray_tpu.rl import models as _models

        cfg = self.config
        if getattr(cfg, "module_spec", None) is not None:
            from ray_tpu.rl.rl_module import Catalog

            return Catalog.build_params(self.spec, cfg.module_spec,
                                        cfg.seed)
        return _models.init_policy(jax.random.key(cfg.seed), self.spec,
                                   cfg.hidden)

    def gather_tolerant(self, refs: List) -> List:
        """Per-ref get that DROPS failed results instead of failing the
        iteration (reference: FaultTolerantActorManager.foreach_worker
        with mark_healthy semantics). Raises only when everything failed —
        a fleet-wide outage is not survivable. The dead runner's actor
        restarts in the background (max_restarts) and serves the next
        iteration."""
        out, last_err = [], None
        for ref in refs:
            try:
                out.append(ray_tpu.get(ref))
            except Exception as e:  # noqa: BLE001 — fragment loss, not fatal
                last_err = e
                logger.warning("env-runner call failed (%s: %s) — dropping "
                               "this fragment; the runner restarts if it "
                               "has budget", type(e).__name__,
                               str(e)[:120])
        if not out and refs:
            raise last_err
        return out

    def synchronous_sample(self, params) -> Dict[str, np.ndarray]:
        """Fan out sample() to the runner fleet and concat fragments
        (reference: ``rollout_ops.synchronous_parallel_sample``); tolerates
        individual runner deaths (fragments dropped for the iteration)."""
        batches = self.gather_tolerant([r.sample.remote(params)
                                        for r in self.runners])
        self._sync_connectors()
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in batches[0]}
        n = len(batch["rewards"])
        # drop per-fragment extras (e.g. [N]-shaped bootstrap values) that
        # can't be row-sliced with the [T*N] columns by minibatch updates
        batch = {k: v for k, v in batch.items() if len(v) == n}
        self._env_steps_total += n
        return batch

    def _sync_connectors(self) -> None:
        """Merge runner connector deltas into the global state, broadcast
        back — every runner then normalizes with the FLEET's statistics."""
        if self._conn_pipeline is None:
            return
        deltas = self.gather_tolerant([r.pop_connector_deltas.remote()
                                       for r in self.runners])
        self._connector_state = self._conn_pipeline.merge_deltas(
            self._connector_state, [d for d in deltas if d is not None])
        try:
            self.gather_tolerant(
                [r.set_connector_globals.remote(self._connector_state)
                 for r in self.runners])
        except Exception:  # noqa: BLE001 — rebroadcast next iteration
            pass

    def collect_episode_stats(self) -> Dict[str, float]:
        stats = self.gather_tolerant([r.episode_stats.remote()
                                      for r in self.runners])
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        episodes = sum(s["episodes"] for s in stats)
        if returns:
            self._return_window.extend(returns)
            self._return_window = self._return_window[-100:]
        mean_ret = (float(np.mean(self._return_window))
                    if self._return_window else float("nan"))
        return {"episodes_this_iter": episodes,
                "episode_return_mean": mean_ret}

    # ---- checkpointing ----

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        import jax

        params = jax.tree_util.tree_map(np.asarray, self.get_params())
        return {"params": params,
                "env_steps_total": self._env_steps_total,
                "connector_state": self._connector_state,
                "extra": self.get_extra_state()}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
        self._connector_state = checkpoint.get("connector_state")
        if self._connector_state is not None and self._conn_pipeline:
            ray_tpu.get([r.set_connector_globals.remote(self._connector_state)
                         for r in self.runners])
        self.set_extra_state(checkpoint.get("extra"))

    def get_params(self):
        return self.learner.get_params()

    def set_params(self, params) -> None:
        self.learner.set_params(params)

    def get_extra_state(self):
        return None

    def set_extra_state(self, state) -> None:
        pass

    # standalone convenience mirroring the reference's Algorithm.save/restore
    def save(self, checkpoint_dir: str) -> Optional[str]:  # type: ignore[override]
        return super().save(checkpoint_dir)

    @classmethod
    def from_checkpoint(cls, checkpoint_dir: str,
                        config: Optional[AlgorithmConfig] = None
                        ) -> "Algorithm":
        algo = (config or cls.get_default_config()).build()
        algo.restore(checkpoint_dir)
        return algo

    def train(self) -> Dict[str, Any]:
        return super().train()

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Roll out the EXPLOITATION policy and report episode returns
        (reference: ``Algorithm.evaluate``). Base implementation samples
        through the env-runner fleet with ``_eval_params()`` (greedy:
        epsilon/exploration-noise off) and leaves training state — the
        env-step counter, the episode-return window, exploration
        schedules — untouched. Fleet-less algorithms (ES/ARS/bandits/
        QMIX/AlphaZero) override."""
        import time

        if not self.runners:
            raise ValueError(
                f"{type(self).__name__} has no env runners; evaluate is "
                "not supported")
        if hasattr(self, "_eval_params"):
            params = self._eval_params()
        elif hasattr(self, "_runner_params"):
            params = self._runner_params()
        else:
            params = self.get_params()
        episodes_seen = 0
        steps_before = self._env_steps_total
        saved_window = self._return_window
        saved_conn = self._connector_state
        self._return_window = []  # eval episodes only
        try:
            deadline = time.monotonic() + 300
            while episodes_seen < num_episodes \
                    and time.monotonic() < deadline:
                self.synchronous_sample(params)
                stats = self.collect_episode_stats()
                episodes_seen += stats["episodes_this_iter"]
            mean_ret = (float(np.mean(self._return_window))
                        if self._return_window else float("nan"))
        finally:
            # evaluation must not advance exploration/stop schedules,
            # pollute the training return window, or shift the fleet's
            # connector (obs-filter) statistics
            self._env_steps_total = steps_before
            self._return_window = saved_window
            if self._conn_pipeline is not None:
                # unconditional: saved_conn=None (evaluate before any
                # train) must also roll the fleet back — set_globals(None)
                # resets every stage to pristine statistics
                self._connector_state = saved_conn
                ray_tpu.get([
                    r.set_connector_globals.remote(saved_conn)
                    for r in self.runners])
        return {"episodes": episodes_seen,
                "episode_return_mean": mean_ret}

    def stop(self) -> None:
        # runners (env-runner fleets) and _workers (ES/ARS episode-eval
        # fleets) both hold cluster CPUs; release them all
        for r in (list(getattr(self, "runners", []))
                  + list(getattr(self, "_workers", []))):
            try:
                ray_tpu.kill(r, no_restart=True)
            except Exception:
                pass
