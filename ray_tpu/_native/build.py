"""Build the rt_native C extension in place.

Run: ``python -m ray_tpu._native.build``  (or it happens lazily on first
import through ``ray_tpu._native``). Uses g++ directly — no setuptools
machinery, no network. The .so lands next to this file; a content hash of
the source gates rebuilds.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "rt_native.cpp")
SO = os.path.join(_DIR, f"rt_native{sysconfig.get_config_var('EXT_SUFFIX')}")
STAMP = os.path.join(_DIR, ".build_hash")


def _src_hash() -> str:
    return hashlib.sha256(open(SRC, "rb").read()).hexdigest()


def build(force: bool = False, quiet: bool = True) -> str:
    """Compile if needed; returns the .so path. Raises on compile failure."""
    if (not force and os.path.exists(SO) and os.path.exists(STAMP)
            and open(STAMP).read().strip() == _src_hash()):
        return SO
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-Wall",
        f"-I{include}", SRC, "-o", SO + ".tmp",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        if not quiet:
            sys.stderr.write(proc.stderr)
        raise RuntimeError(f"rt_native build failed:\n{proc.stderr[-2000:]}")
    os.replace(SO + ".tmp", SO)
    with open(STAMP, "w") as f:
        f.write(_src_hash())
    return SO


if __name__ == "__main__":
    print(build(force="--force" in sys.argv, quiet=False))
