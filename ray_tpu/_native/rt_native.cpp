// rt_native: the framework's native runtime core (CPython C API, no pybind).
//
// Reference analogs (all C++ there too):
//   - memory monitor: src/ray/common/memory_monitor.h (cgroup/proc polling
//     feeding raylet/worker_killing_policy.cc)
//   - chunk integrity: src/ray/object_manager/chunk_object_reader.h pairs
//     with crc32 checks in the object manager protocol
//   - append-only store: src/ray/gcs/store_client/redis_store_client.cc's
//     role (durable KV behind the GCS tables)
//
// Exposed:
//   crc32c(bytes-like[, init]) -> int          (Castagnoli, slice-by-8)
//   memory_info() -> dict                      (system + cgroup v1/v2)
//   process_rss(pid) -> int                    (bytes; -1 if gone)
//   process_memory(pids) -> list[(pid, rss)]   (one pass, sorted desc)
//   LogKV(path)                                (append-only durable dict)
//     .put(key: str, value: bytes)  .get(key) -> bytes|None
//     .delete(key)  .keys() -> list[str]  .compact()  .close()
//     .sync()       len(kv)
//
// Build: python -m ray_tpu._native.build  (g++ via setuptools, no network).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

// ---------------------------------------------------------------- crc32c ---

static uint32_t crc32c_table[8][256];
static bool crc32c_ready = false;

static void crc32c_init_tables() {
  const uint32_t poly = 0x82f63b78u;  // reflected Castagnoli
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    crc32c_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc32c_table[0][i];
    for (int t = 1; t < 8; t++) {
      c = crc32c_table[0][c & 0xff] ^ (c >> 8);
      crc32c_table[t][i] = c;
    }
  }
  crc32c_ready = true;
}

static uint32_t crc32c_run(uint32_t crc, const uint8_t* buf, size_t len) {
  crc = ~crc;
  while (len && ((uintptr_t)buf & 7)) {
    crc = crc32c_table[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t word;
    memcpy(&word, buf, 8);
    word ^= crc;  // little-endian assumption (fine for x86/arm linux)
    crc = crc32c_table[7][word & 0xff] ^ crc32c_table[6][(word >> 8) & 0xff] ^
          crc32c_table[5][(word >> 16) & 0xff] ^
          crc32c_table[4][(word >> 24) & 0xff] ^
          crc32c_table[3][(word >> 32) & 0xff] ^
          crc32c_table[2][(word >> 40) & 0xff] ^
          crc32c_table[1][(word >> 48) & 0xff] ^
          crc32c_table[0][(word >> 56) & 0xff];
    buf += 8;
    len -= 8;
  }
  while (len--) crc = crc32c_table[0][(crc ^ *buf++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

static PyObject* py_crc32c(PyObject*, PyObject* args) {
  Py_buffer view;
  unsigned int init = 0;
  if (!PyArg_ParseTuple(args, "y*|I", &view, &init)) return nullptr;
  uint32_t crc;
  Py_BEGIN_ALLOW_THREADS
  crc = crc32c_run((uint32_t)init, (const uint8_t*)view.buf, (size_t)view.len);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong(crc);
}

// ---------------------------------------------------------- memory_info ----

static long long read_ll_file(const char* path) {
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  char buf[64];
  if (!fgets(buf, sizeof buf, f)) {
    fclose(f);
    return -1;
  }
  fclose(f);
  if (strncmp(buf, "max", 3) == 0) return -1;  // cgroup v2 "max" = unlimited
  return atoll(buf);
}

// parse /proc/meminfo keys (kB units)
static void read_meminfo(long long* total, long long* available) {
  *total = -1;
  *available = -1;
  FILE* f = fopen("/proc/meminfo", "r");
  if (!f) return;
  char line[256];
  while (fgets(line, sizeof line, f)) {
    long long v;
    if (sscanf(line, "MemTotal: %lld kB", &v) == 1) *total = v * 1024;
    else if (sscanf(line, "MemAvailable: %lld kB", &v) == 1)
      *available = v * 1024;
    if (*total >= 0 && *available >= 0) break;
  }
  fclose(f);
}

static PyObject* py_memory_info(PyObject*, PyObject*) {
  long long sys_total, sys_avail;
  long long cg_limit = -1, cg_used = -1;
  Py_BEGIN_ALLOW_THREADS
  read_meminfo(&sys_total, &sys_avail);
  // cgroup v2 first, then v1 (the reference checks both the same way)
  cg_limit = read_ll_file("/sys/fs/cgroup/memory.max");
  if (cg_limit >= 0) {
    cg_used = read_ll_file("/sys/fs/cgroup/memory.current");
  } else {
    cg_limit = read_ll_file("/sys/fs/cgroup/memory/memory.limit_in_bytes");
    if (cg_limit >= (long long)1 << 60) cg_limit = -1;  // v1 "unlimited"
    if (cg_limit >= 0)
      cg_used = read_ll_file("/sys/fs/cgroup/memory/memory.usage_in_bytes");
  }
  Py_END_ALLOW_THREADS
  long long total = sys_total, used = sys_total - sys_avail;
  if (cg_limit > 0 && (sys_total < 0 || cg_limit < sys_total)) {
    total = cg_limit;
    if (cg_used >= 0) used = cg_used;
  }
  return Py_BuildValue(
      "{s:L,s:L,s:L,s:L,s:L,s:L}", "total", total, "used", used, "available",
      total >= 0 && used >= 0 ? total - used : -1, "system_total", sys_total,
      "cgroup_limit", cg_limit, "cgroup_used", cg_used);
}

static long long rss_of(long pid) {
  char path[64];
  snprintf(path, sizeof path, "/proc/%ld/statm", pid);
  FILE* f = fopen(path, "r");
  if (!f) return -1;
  long long size_pages, rss_pages;
  int n = fscanf(f, "%lld %lld", &size_pages, &rss_pages);
  fclose(f);
  if (n != 2) return -1;
  return rss_pages * (long long)sysconf(_SC_PAGESIZE);
}

static PyObject* py_process_rss(PyObject*, PyObject* args) {
  long pid;
  if (!PyArg_ParseTuple(args, "l", &pid)) return nullptr;
  long long rss;
  Py_BEGIN_ALLOW_THREADS
  rss = rss_of(pid);
  Py_END_ALLOW_THREADS
  return PyLong_FromLongLong(rss);
}

static PyObject* py_process_memory(PyObject*, PyObject* args) {
  PyObject* pids;
  if (!PyArg_ParseTuple(args, "O", &pids)) return nullptr;
  PyObject* seq = PySequence_Fast(pids, "expected a sequence of pids");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::vector<std::pair<long long, long>> out;
  out.reserve(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    long pid = PyLong_AsLong(PySequence_Fast_GET_ITEM(seq, i));
    if (pid == -1 && PyErr_Occurred()) {
      Py_DECREF(seq);
      return nullptr;
    }
    long long rss = rss_of(pid);
    if (rss >= 0) out.emplace_back(rss, pid);
  }
  Py_DECREF(seq);
  std::sort(out.rbegin(), out.rend());  // largest RSS first
  PyObject* list = PyList_New((Py_ssize_t)out.size());
  if (!list) return nullptr;
  for (size_t i = 0; i < out.size(); i++) {
    PyList_SET_ITEM(list, (Py_ssize_t)i,
                    Py_BuildValue("(lL)", out[i].second, out[i].first));
  }
  return list;
}

// -------------------------------------------------------------- LogKV ------
//
// Durable append-only KV: records are
//   [u32 crc over rest][u32 klen][u32 vlen|0xffffffff=tombstone][key][value]
// Replay on open rebuilds the in-memory index; compact() rewrites live
// entries to <path>.compact then renames (atomic on POSIX).

struct LogKVObject {
  PyObject_HEAD
  std::map<std::string, std::string>* table;
  std::string* path;
  int fd;
};

static int logkv_append(LogKVObject* self, const std::string& key,
                        const char* val, uint32_t vlen, bool tombstone) {
  uint32_t klen = (uint32_t)key.size();
  uint32_t vfield = tombstone ? 0xffffffffu : vlen;
  std::string rec;
  rec.reserve(12 + klen + (tombstone ? 0 : vlen));
  rec.append(8, '\0');  // klen+vfield placeholder (crc prepended later)
  memcpy(&rec[0], &klen, 4);
  memcpy(&rec[4], &vfield, 4);
  rec.append(key);
  if (!tombstone && vlen) rec.append(val, vlen);
  uint32_t crc =
      crc32c_run(0, (const uint8_t*)rec.data(), rec.size());
  std::string frame;
  frame.reserve(4 + rec.size());
  frame.append((const char*)&crc, 4);
  frame.append(rec);
  const char* p = frame.data();
  size_t left = frame.size();
  while (left) {
    ssize_t w = write(self->fd, p, left);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += w;
    left -= (size_t)w;
  }
  return 0;
}

// zlib-polynomial crc32: legacy WAL files written by the pure-Python
// fallback of older builds framed records with zlib.crc32. Replay accepts
// either algorithm per record so a toolchain appearing between restarts
// can't silently discard the whole durable KV as a corrupt tail.
static uint32_t crc32_zlib_run(uint32_t crc, const uint8_t* buf, size_t len) {
  static uint32_t table[256];
  static bool ready = false;
  if (!ready) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    ready = true;
  }
  crc = ~crc;
  while (len--) crc = table[(crc ^ *buf++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

static int logkv_replay(LogKVObject* self) {
  FILE* f = fopen(self->path->c_str(), "rb");
  if (!f) return 0;  // fresh store
  const char* stop = nullptr;
  long pos = 0;
  for (;;) {
    pos = ftell(f);
    uint8_t hdr[12];
    size_t n = fread(hdr, 1, 12, f);
    if (n == 0) break;
    if (n < 12) {  // torn tail record: ignore (crash mid-append)
      stop = "torn header";
      break;
    }
    uint32_t crc, klen, vfield;
    memcpy(&crc, hdr, 4);
    memcpy(&klen, hdr + 4, 4);
    memcpy(&vfield, hdr + 8, 4);
    bool tombstone = vfield == 0xffffffffu;
    uint32_t vlen = tombstone ? 0 : vfield;
    if (klen > (1u << 24) || vlen > (1u << 30)) {
      stop = "implausible record lengths";
      break;
    }
    std::string body(8 + klen + vlen, '\0');
    memcpy(&body[0], hdr + 4, 8);
    if (fread(&body[8], 1, klen + vlen, f) < klen + vlen) {
      stop = "torn body";
      break;
    }
    if (crc32c_run(0, (const uint8_t*)body.data(), body.size()) != crc &&
        crc32_zlib_run(0, (const uint8_t*)body.data(), body.size()) != crc) {
      stop = "checksum mismatch";
      break;
    }
    std::string key = body.substr(8, klen);
    if (tombstone)
      self->table->erase(key);
    else
      (*self->table)[key] = body.substr(8 + klen, vlen);
  }
  if (stop) {
    fseek(f, 0, SEEK_END);
    long size = ftell(f);
    if (pos < size) {
      fprintf(stderr,
              "rt_native LogKV: replay of %s stopped at offset %ld of %ld "
              "(%s); %ld trailing bytes truncated, %zu keys recovered\n",
              self->path->c_str(), pos, size, stop, size - pos,
              self->table->size());
      // Truncate the unreplayable tail before the O_APPEND open: appends
      // landing after a surviving torn tail would be skipped by every
      // future replay — acked writes silently lost on each restart.
      if (truncate(self->path->c_str(), pos) != 0)
        fprintf(stderr, "rt_native LogKV: truncate(%s, %ld) failed: %s\n",
                self->path->c_str(), pos, strerror(errno));
    }
  }
  fclose(f);
  return 0;
}

static PyObject* LogKV_new(PyTypeObject* type, PyObject*, PyObject*) {
  LogKVObject* self = (LogKVObject*)type->tp_alloc(type, 0);
  if (self) {
    self->table = new std::map<std::string, std::string>();
    self->path = new std::string();
    self->fd = -1;
  }
  return (PyObject*)self;
}

static int LogKV_init(LogKVObject* self, PyObject* args, PyObject*) {
  const char* path;
  if (!PyArg_ParseTuple(args, "s", &path)) return -1;
  *self->path = path;
  logkv_replay(self);
  self->fd = open(path, O_WRONLY | O_CREAT | O_APPEND, 0600);
  if (self->fd < 0) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return -1;
  }
  return 0;
}

static void LogKV_dealloc(LogKVObject* self) {
  if (self->fd >= 0) close(self->fd);
  delete self->table;
  delete self->path;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static PyObject* LogKV_put(LogKVObject* self, PyObject* args) {
  const char* key;
  Py_buffer val;
  if (!PyArg_ParseTuple(args, "sy*", &key, &val)) return nullptr;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = logkv_append(self, key, (const char*)val.buf, (uint32_t)val.len, false);
  Py_END_ALLOW_THREADS
  if (rc == 0)
    (*self->table)[key] = std::string((const char*)val.buf, (size_t)val.len);
  PyBuffer_Release(&val);
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "LogKV append failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* LogKV_get(LogKVObject* self, PyObject* args) {
  const char* key;
  if (!PyArg_ParseTuple(args, "s", &key)) return nullptr;
  auto it = self->table->find(key);
  if (it == self->table->end()) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(it->second.data(),
                                   (Py_ssize_t)it->second.size());
}

static PyObject* LogKV_delete(LogKVObject* self, PyObject* args) {
  const char* key;
  if (!PyArg_ParseTuple(args, "s", &key)) return nullptr;
  auto it = self->table->find(key);
  if (it == self->table->end()) Py_RETURN_FALSE;
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = logkv_append(self, key, nullptr, 0, true);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetString(PyExc_OSError, "LogKV append failed");
    return nullptr;
  }
  self->table->erase(it);
  Py_RETURN_TRUE;
}

static PyObject* LogKV_keys(LogKVObject* self, PyObject*) {
  PyObject* list = PyList_New((Py_ssize_t)self->table->size());
  if (!list) return nullptr;
  Py_ssize_t i = 0;
  for (auto& kv : *self->table) {
    PyList_SET_ITEM(list, i++,
                    PyUnicode_FromStringAndSize(kv.first.data(),
                                                (Py_ssize_t)kv.first.size()));
  }
  return list;
}

static PyObject* LogKV_sync(LogKVObject* self, PyObject*) {
  int rc;
  Py_BEGIN_ALLOW_THREADS
  rc = fsync(self->fd);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  Py_RETURN_NONE;
}

static PyObject* LogKV_compact(LogKVObject* self, PyObject*) {
  std::string tmp = *self->path + ".compact";
  int tfd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0600);
  if (tfd < 0) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, tmp.c_str());
    return nullptr;
  }
  int old_fd = self->fd;
  self->fd = tfd;
  int rc = 0;
  Py_BEGIN_ALLOW_THREADS
  for (auto& kv : *self->table) {
    if (logkv_append(self, kv.first, kv.second.data(),
                     (uint32_t)kv.second.size(), false) != 0) {
      rc = -1;
      break;
    }
  }
  if (rc == 0) rc = fsync(tfd);
  Py_END_ALLOW_THREADS
  if (rc != 0 || rename(tmp.c_str(), self->path->c_str()) != 0) {
    close(tfd);
    self->fd = old_fd;
    unlink(tmp.c_str());
    PyErr_SetString(PyExc_OSError, "LogKV compact failed");
    return nullptr;
  }
  close(old_fd);
  Py_RETURN_NONE;
}

static PyObject* LogKV_close(LogKVObject* self, PyObject*) {
  if (self->fd >= 0) {
    close(self->fd);
    self->fd = -1;
  }
  Py_RETURN_NONE;
}

static Py_ssize_t LogKV_len(PyObject* self) {
  return (Py_ssize_t)((LogKVObject*)self)->table->size();
}

static PyMethodDef LogKV_methods[] = {
    {"put", (PyCFunction)LogKV_put, METH_VARARGS, "put(key, bytes)"},
    {"get", (PyCFunction)LogKV_get, METH_VARARGS, "get(key) -> bytes|None"},
    {"delete", (PyCFunction)LogKV_delete, METH_VARARGS,
     "delete(key) -> bool"},
    {"keys", (PyCFunction)LogKV_keys, METH_NOARGS, "keys() -> list[str]"},
    {"sync", (PyCFunction)LogKV_sync, METH_NOARGS, "fsync the log"},
    {"compact", (PyCFunction)LogKV_compact, METH_NOARGS,
     "rewrite live entries, drop tombstones"},
    {"close", (PyCFunction)LogKV_close, METH_NOARGS, "close the fd"},
    {nullptr, nullptr, 0, nullptr}};

static PySequenceMethods LogKV_as_seq = {
    LogKV_len, nullptr, nullptr, nullptr, nullptr,
    nullptr,   nullptr, nullptr, nullptr, nullptr};

static PyTypeObject LogKVType = {
    PyVarObject_HEAD_INIT(nullptr, 0) "rt_native.LogKV", /* tp_name */
    sizeof(LogKVObject)};

// ----------------------------------------------------------------- module --

static PyMethodDef rt_methods[] = {
    {"crc32c", py_crc32c, METH_VARARGS, "crc32c(data[, init]) -> int"},
    {"memory_info", py_memory_info, METH_NOARGS,
     "system+cgroup memory -> dict"},
    {"process_rss", py_process_rss, METH_VARARGS, "process_rss(pid) -> int"},
    {"process_memory", py_process_memory, METH_VARARGS,
     "process_memory(pids) -> [(pid, rss)] sorted desc"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef rt_module = {PyModuleDef_HEAD_INIT, "rt_native",
                                       "ray_tpu native runtime core", -1,
                                       rt_methods};

PyMODINIT_FUNC PyInit_rt_native(void) {
  crc32c_init_tables();
  LogKVType.tp_basicsize = sizeof(LogKVObject);
  LogKVType.tp_flags = Py_TPFLAGS_DEFAULT;
  LogKVType.tp_doc = "append-only durable KV (crc32c-framed log + index)";
  LogKVType.tp_new = LogKV_new;
  LogKVType.tp_init = (initproc)LogKV_init;
  LogKVType.tp_dealloc = (destructor)LogKV_dealloc;
  LogKVType.tp_methods = LogKV_methods;
  LogKVType.tp_as_sequence = &LogKV_as_seq;
  if (PyType_Ready(&LogKVType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&rt_module);
  if (!m) return nullptr;
  Py_INCREF(&LogKVType);
  if (PyModule_AddObject(m, "LogKV", (PyObject*)&LogKVType) < 0) {
    Py_DECREF(&LogKVType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
