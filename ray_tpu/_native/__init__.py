"""Native runtime core with pure-Python fallback.

``from ray_tpu._native import native`` gives the compiled ``rt_native``
module (building it on first use) or ``None`` when no toolchain exists;
the helpers below always work, falling back to Python implementations.
This mirrors the reference's split: C++ runtime primitives
(``memory_monitor.h``, chunked-object crc, gcs store client) under a
Python control plane.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional, Tuple

native = None
_tried = False


def _load():
    global native, _tried
    if _tried:
        return native
    _tried = True
    if os.environ.get("RT_DISABLE_NATIVE"):
        return None
    try:
        from ray_tpu._native.build import build

        build()
        import importlib.util

        from ray_tpu._native.build import SO

        spec = importlib.util.spec_from_file_location("rt_native", SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        native = mod
    except Exception:  # noqa: BLE001 — no toolchain: Python fallbacks below
        native = None
    return native


def crc32c(data, init: int = 0) -> int:
    """Castagnoli CRC of a bytes-like (native) or crc32 fallback. Anything
    that crosses a host boundary must carry ``checksum_kind()`` alongside the
    value and verify with ``checksum(data, kind)`` — a mixed cluster (one
    host with a toolchain, one without) produces different algorithms."""
    n = _load()
    if n is not None:
        return n.crc32c(data, init)
    return zlib.crc32(data, init) & 0xFFFFFFFF


def checksum_kind() -> str:
    return "crc32c" if _load() is not None else "crc32"


_CRC32C_POLY = 0x82F63B78  # reflected Castagnoli
_crc32c_table: Optional[List[int]] = None


def _sw_table() -> List[int]:
    global _crc32c_table
    if _crc32c_table is None:
        t = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (_CRC32C_POLY if c & 1 else 0)
            t.append(c)
        _crc32c_table = t
    return _crc32c_table


def crc32c_sw(data, init: int = 0) -> int:
    """Castagnoli CRC that is ALWAYS Castagnoli: native when available,
    pure-Python table otherwise. Unlike :func:`crc32c` this never silently
    switches algorithm with toolchain availability. The WAL replayers use
    it to verify native-written frames on toolchain-less hosts; it is far
    too slow (GIL-bound byte loop) for write paths or multi-MB payloads —
    those use zlib.crc32 or the tagged ``checksum_kind()`` scheme."""
    n = _load()
    if n is not None:
        return n.crc32c(data, init)
    t = _sw_table()
    crc = (init & 0xFFFFFFFF) ^ 0xFFFFFFFF
    for b in bytes(data):
        crc = t[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def checksum(data, kind: str) -> Optional[int]:
    """Compute the named checksum, or None if this host can't (no native
    crc32c and the peer used it) — callers skip verification then."""
    if kind == "crc32":
        return zlib.crc32(data) & 0xFFFFFFFF
    n = _load()
    if kind == "crc32c" and n is not None:
        return n.crc32c(data, 0)
    return None


def memory_info() -> Dict[str, int]:
    """total/used/available bytes, cgroup-aware (v1 and v2)."""
    n = _load()
    if n is not None:
        return n.memory_info()
    total = used = avail = -1
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        pass
    if total >= 0 and avail >= 0:
        used = total - avail
    return {"total": total, "used": used, "available": avail,
            "system_total": total, "cgroup_limit": -1, "cgroup_used": -1}


def process_rss(pid: int) -> int:
    n = _load()
    if n is not None:
        return n.process_rss(pid)
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return -1


def process_memory(pids: List[int]) -> List[Tuple[int, int]]:
    """[(pid, rss_bytes)] for live pids, largest first."""
    n = _load()
    if n is not None:
        return n.process_memory(list(pids))
    out = [(p, process_rss(p)) for p in pids]
    return sorted([x for x in out if x[1] >= 0], key=lambda x: -x[1])


class PyLogKV:
    """Pure-Python LogKV fallback (same on-disk format as the native one).

    Replay accepts BOTH frame checksums — crc32c (native writer) and zlib
    crc32 (this writer) — so a WAL survives the toolchain appearing or
    disappearing between restarts (ADVICE r3: a silent algorithm flip used
    to discard the whole durable KV as a corrupt tail). Writes frame with
    zlib.crc32: it runs at C speed (the pure-Python crc32c table would be
    GIL-bound minutes for the multi-MB runtime-env packages the GCS WAL
    stores), and the native replayer accepts it.
    """

    _TOMB = 0xFFFFFFFF

    def __init__(self, path: str):
        import struct

        self._path = path
        self._table: Dict[str, bytes] = {}
        self._struct = struct
        if os.path.exists(path):
            self._replay()
        self._f = open(path, "ab")

    def _replay(self) -> None:
        s = self._struct
        size = os.path.getsize(self._path)
        reason = None
        self._algos = [lambda b: zlib.crc32(b) & 0xFFFFFFFF, crc32c_sw]
        pos = 0
        with open(self._path, "rb") as f:
            while True:
                pos = f.tell()
                hdr = f.read(12)
                if len(hdr) == 0:
                    break
                if len(hdr) < 12:
                    reason = "torn header"
                    break
                crc, klen, vfield = s.unpack("<III", hdr)
                tomb = vfield == self._TOMB
                vlen = 0 if tomb else vfield
                if klen > 1 << 24 or vlen > 1 << 30:
                    reason = "implausible record lengths"
                    break
                body = f.read(klen + vlen)
                if len(body) < klen + vlen:
                    reason = "torn body"
                    break
                rec = hdr[4:] + body
                # Try the last-matched algorithm first: zlib.crc32 is
                # C-speed, crc32c_sw a Python byte loop — a homogeneous
                # file (the common case) should pay the slow check at most
                # once, not per record.
                if self._algos[0](rec) != crc:
                    if self._algos[1](rec) != crc:
                        reason = "checksum mismatch"
                        break
                    self._algos.reverse()
                key = body[:klen].decode()
                if tomb:
                    self._table.pop(key, None)
                else:
                    self._table[key] = body[klen:]
        if reason is not None and pos < size:
            import logging

            logging.getLogger("ray_tpu.native").warning(
                "LogKV replay of %s stopped at offset %d of %d (%s): "
                "%d trailing bytes truncated. If this is more than one "
                "torn record the WAL may be corrupt — recovered %d keys.",
                self._path, pos, size, reason, size - pos, len(self._table))
            # Truncate the unreplayable tail BEFORE appending: records
            # written after a surviving torn tail would sit behind it and
            # be invisible to every future replay — acked-then-lost on
            # each subsequent restart.
            os.truncate(self._path, pos)

    def _append(self, key: str, value: Optional[bytes]) -> None:
        s = self._struct
        kb = key.encode()
        vfield = self._TOMB if value is None else len(value)
        body = s.pack("<II", len(kb), vfield) + kb + (value or b"")
        self._f.write(
            s.pack("<I", zlib.crc32(body) & 0xFFFFFFFF) + body)
        self._f.flush()

    def put(self, key: str, value: bytes) -> None:
        self._append(key, bytes(value))
        self._table[key] = bytes(value)

    def get(self, key: str) -> Optional[bytes]:
        return self._table.get(key)

    def delete(self, key: str) -> bool:
        if key not in self._table:
            return False
        self._append(key, None)
        del self._table[key]
        return True

    def keys(self):
        return list(self._table)

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def compact(self) -> None:
        tmp = self._path + ".compact"
        old = self._f
        with open(tmp, "wb"):
            pass
        self._f = open(tmp, "ab")
        try:
            for k, v in self._table.items():
                self._append(k, v)
            self.sync()
            os.replace(tmp, self._path)
            old.close()
        except Exception:
            self._f.close()
            self._f = old
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def close(self) -> None:
        self._f.close()

    def __len__(self) -> int:
        return len(self._table)


def LogKV(path: str):
    """Durable append-only KV: native if available, Python otherwise."""
    n = _load()
    if n is not None:
        return n.LogKV(path)
    return PyLogKV(path)
