"""Runtime context introspection (reference: ``python/ray/runtime_context.py``)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.worker import global_worker


class RuntimeContext:
    @property
    def job_id(self):
        return global_worker().job_id

    @property
    def task_id(self):
        return global_worker().current_task_id()

    @property
    def actor_id(self):
        return global_worker().current_actor_id()

    def get_job_id(self) -> str:
        return global_worker().job_id.hex()

    def get_task_id(self) -> Optional[str]:
        tid = global_worker().current_task_id()
        return tid.hex() if tid else None

    def get_actor_id(self) -> Optional[str]:
        aid = global_worker().current_actor_id()
        return aid.hex() if aid else None

    def get_node_id(self) -> str:
        backend = global_worker().backend
        node_id = getattr(backend, "node_id", None)
        if node_id is not None:
            return node_id
        nodes = backend.nodes()
        return nodes[0]["node_id"] if nodes else ""

    def get_tpu_ids(self) -> List[int]:
        """Chip indices assigned to the current worker (the TPU analog of the
        reference's ``get_gpu_ids``), parsed from TPU_VISIBLE_CHIPS."""
        import os

        from ray_tpu._private.config import get_config

        raw = os.environ.get(get_config().tpu_visible_chips_env)
        if not raw:
            return []
        return [int(x) for x in raw.split(",") if x != ""]

    def get_assigned_resources(self) -> Dict[str, float]:
        return {}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
