"""Cluster scheduling: node selection policies + the cluster resource view.

Shared by the GCS (actor/PG scheduling, task spillback routing) and each
raylet (local queueing + spillback decisions) — the two halves of the
reference's two-level design (``ClusterTaskManager``/``LocalTaskManager``).
"""

from ray_tpu.scheduler.policy import (  # noqa: F401
    HybridPolicy,
    NodeAffinityPolicy,
    NodeLabelPolicy,
    SpreadPolicy,
    pick_node,
)
