"""Node-selection policies.

Reference analog: ``src/ray/raylet/scheduling/policy/`` — the hybrid policy's
rationale (``hybrid_scheduling_policy.h:29-48``) is kept: prefer nodes that
can run the task NOW over merely-feasible ones; rank by critical-resource
utilization truncated below a spread threshold (so lightly-loaded nodes tie
and small tasks pack rather than fragment); break ties randomly among the
top candidates with the local/preferred node winning outright ties.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import get_config
from ray_tpu.core.resources import NodeResources, ResourceSet


class HybridPolicy:
    def pick(self, nodes: Dict[str, NodeResources], req: ResourceSet,
             preferred: Optional[str] = None,
             rng: Optional[random.Random] = None) -> Optional[str]:
        cfg = get_config()
        rng = rng or random
        available: List[Tuple[float, str]] = []
        feasible: List[str] = []
        for node_id, nr in nodes.items():
            if not nr.is_feasible(req):
                continue
            feasible.append(node_id)
            if nr.can_fit(req):
                util = nr.utilization(req)
                if util < cfg.scheduler_spread_threshold:
                    util = 0.0  # truncate: lightly-loaded nodes tie
                available.append((util, node_id))
        if available:
            best = min(u for u, _ in available)
            candidates = [n for u, n in available if u == best]
            if preferred in candidates:
                return preferred
            return rng.choice(candidates)
        if feasible:
            # Nothing can run it now; queue at a feasible node (prefer local).
            if preferred in feasible:
                return preferred
            return rng.choice(feasible)
        return None


class SpreadPolicy:
    """Round-robin across nodes that can fit (reference:
    ``spread_scheduling_policy.h:27``)."""

    def __init__(self):
        self._rr = 0

    def pick(self, nodes, req, preferred=None, rng=None):
        fitting = sorted(n for n, nr in nodes.items() if nr.can_fit(req))
        if not fitting:
            fitting = sorted(n for n, nr in nodes.items() if nr.is_feasible(req))
        if not fitting:
            return None
        self._rr += 1
        return fitting[self._rr % len(fitting)]


class NodeAffinityPolicy:
    def __init__(self, node_id: str, soft: bool):
        self.node_id = node_id
        self.soft = soft

    def pick(self, nodes, req, preferred=None, rng=None):
        nr = nodes.get(self.node_id)
        if nr is not None and nr.is_feasible(req):
            return self.node_id
        if self.soft:
            return HybridPolicy().pick(nodes, req, preferred, rng)
        return None


# Module-level instance so the round-robin counter persists across calls.
_SPREAD = SpreadPolicy()


def pick_node(strategy, nodes: Dict[str, NodeResources], req: ResourceSet,
              preferred: Optional[str] = None) -> Optional[str]:
    """Dispatch on a TaskSpec SchedulingStrategy."""
    kind = getattr(strategy, "kind", "DEFAULT")
    if kind == "SPREAD":
        return _SPREAD.pick(nodes, req, preferred)
    if kind == "NODE_AFFINITY":
        return NodeAffinityPolicy(strategy.node_id_hex, strategy.soft).pick(
            nodes, req, preferred)
    return HybridPolicy().pick(nodes, req, preferred)
