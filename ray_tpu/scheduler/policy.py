"""Node-selection policies.

Reference analog: ``src/ray/raylet/scheduling/policy/`` — the hybrid policy's
rationale (``hybrid_scheduling_policy.h:29-48``) is kept: prefer nodes that
can run the task NOW over merely-feasible ones; rank by critical-resource
utilization truncated below a spread threshold (so lightly-loaded nodes tie
and small tasks pack rather than fragment); break ties randomly among the
top candidates with the local/preferred node winning outright ties.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import get_config
from ray_tpu.core.resources import NodeResources, ResourceSet


class HybridPolicy:
    def pick(self, nodes: Dict[str, NodeResources], req: ResourceSet,
             preferred: Optional[str] = None,
             rng: Optional[random.Random] = None) -> Optional[str]:
        cfg = get_config()
        rng = rng or random
        available: List[Tuple[float, str]] = []
        feasible: List[str] = []
        for node_id, nr in nodes.items():
            if not nr.is_feasible(req):
                continue
            feasible.append(node_id)
            if nr.can_fit(req):
                util = nr.utilization(req)
                if util < cfg.scheduler_spread_threshold:
                    util = 0.0  # truncate: lightly-loaded nodes tie
                available.append((util, node_id))
        if available:
            # Rank by critical-resource utilization and pick randomly among
            # the TOP-K (reference hybrid_scheduling_policy.h:29-48): pure
            # best-node packing funnels every scheduler's next task at the
            # same node (noisy neighbor, worker-pool cold-start pileup);
            # pure random fragments. The preferred/local node wins outright
            # ties at the best score.
            available.sort()
            best = available[0][0]
            if any(n == preferred and u == best for u, n in available):
                return preferred
            n_tied = sum(1 for u, _ in available if u == best)
            k = max(1, n_tied,
                    int(cfg.scheduler_top_k_fraction * len(available) + .999))
            return rng.choice([n for _, n in available[:k]])
        if feasible:
            # Nothing can run it now; queue at a feasible node (prefer local).
            if preferred in feasible:
                return preferred
            return rng.choice(feasible)
        return None


class SpreadPolicy:
    """Round-robin across nodes that can fit (reference:
    ``spread_scheduling_policy.h:27``)."""

    def __init__(self):
        self._rr = 0

    def pick(self, nodes, req, preferred=None, rng=None):
        fitting = sorted(n for n, nr in nodes.items() if nr.can_fit(req))
        if not fitting:
            fitting = sorted(n for n, nr in nodes.items() if nr.is_feasible(req))
        if not fitting:
            return None
        self._rr += 1
        return fitting[self._rr % len(fitting)]


class NodeAffinityPolicy:
    def __init__(self, node_id: str, soft: bool):
        self.node_id = node_id
        self.soft = soft

    def pick(self, nodes, req, preferred=None, rng=None):
        nr = nodes.get(self.node_id)
        if nr is not None and nr.is_feasible(req):
            return self.node_id
        if self.soft:
            return HybridPolicy().pick(nodes, req, preferred, rng)
        return None


def _label_matches(expr, value: Optional[str]) -> bool:
    """One label match expression against a node's label value (None =
    absent). See ``NodeLabelStrategy`` for the expression forms."""
    if isinstance(expr, (list, tuple, set)):
        return value is not None and value in expr
    if expr == "*":
        return value is not None
    if expr == "!*":
        return value is None
    if isinstance(expr, str) and expr.startswith("!"):
        return value != expr[1:]
    return value == expr


class NodeLabelPolicy:
    """Hard label constraints filter; soft constraints prefer (reference:
    ``NodeLabelSchedulingPolicy`` — hard eliminates, soft splits the
    survivors into preferred/fallback tiers). Within a tier, the hybrid
    ranking applies."""

    def __init__(self, hard: Dict, soft: Dict):
        self.hard = hard or {}
        self.soft = soft or {}

    def _matches(self, nr, constraints: Dict) -> bool:
        return all(_label_matches(expr, nr.labels.get(key))
                   for key, expr in constraints.items())

    def pick(self, nodes, req, preferred=None, rng=None):
        hard_ok = {nid: nr for nid, nr in nodes.items()
                   if self._matches(nr, self.hard)}
        if not hard_ok:
            return None  # infeasible until a matching node joins
        if self.soft:
            soft_ok = {nid: nr for nid, nr in hard_ok.items()
                       if self._matches(nr, self.soft)}
            picked = HybridPolicy().pick(soft_ok, req, preferred, rng) \
                if soft_ok else None
            # a soft preference only holds if its node can run the task
            # NOW — a full soft-matching node must not shadow an idle
            # hard-tier node (HybridPolicy returns queue targets too)
            if picked is not None and soft_ok[picked].can_fit(req):
                return picked
        return HybridPolicy().pick(hard_ok, req, preferred, rng)


def strategy_allows_local(strategy, node_id: str,
                          labels: Dict[str, str]) -> bool:
    """May a raylet dispatch this task on ITS OWN node, or must it route?

    Hard NODE_AFFINITY to another node and unsatisfied hard NODE_LABEL
    constraints forbid local execution (reference: these policies filter
    the candidate set BEFORE dispatch; here raylet-push means the local
    queue sees every task first and must decline ineligible ones)."""
    kind = getattr(strategy, "kind", "DEFAULT")
    if kind == "NODE_AFFINITY" and not strategy.soft:
        return strategy.node_id_hex == node_id
    if kind == "NODE_LABEL":
        return all(_label_matches(expr, labels.get(key))
                   for key, expr in (strategy.hard or {}).items())
    return True


# Module-level instance so the round-robin counter persists across calls.
_SPREAD = SpreadPolicy()


def pick_node(strategy, nodes: Dict[str, NodeResources], req: ResourceSet,
              preferred: Optional[str] = None) -> Optional[str]:
    """Dispatch on a TaskSpec SchedulingStrategy."""
    kind = getattr(strategy, "kind", "DEFAULT")
    if kind == "SPREAD":
        return _SPREAD.pick(nodes, req, preferred)
    if kind == "NODE_AFFINITY":
        return NodeAffinityPolicy(strategy.node_id_hex, strategy.soft).pick(
            nodes, req, preferred)
    if kind == "NODE_LABEL":
        return NodeLabelPolicy(strategy.hard, strategy.soft).pick(
            nodes, req, preferred)
    return HybridPolicy().pick(nodes, req, preferred)
