"""RuntimeEnv: packaging (driver side) + materialization (worker side).

Reference analogs: ``_private/runtime_env/packaging.py`` (zip -> KV under a
content hash, ``gcs://`` URIs), ``working_dir.py`` (download + chdir +
sys.path), ``pip.py`` (dependency install), ``context.py`` (env var
injection). The worker cache key (raylet worker pool) includes the env hash,
so processes are reused only within the same environment — the reference's
worker-pool-keyed-by-runtime-env-hash behavior.

Supported fields:
  - ``working_dir``: local dir (driver packages it) or ``gcs://<hash>`` URI.
  - ``env_vars``: dict of str -> str set in the worker before user code.
  - ``pip``: list of requirement strings / local wheel paths, installed into
    a per-env cache dir that is prepended to ``sys.path`` (no venv spawn —
    same interpreter, isolated site dir).
  - ``py_modules``: list of local package dirs (reference:
    ``runtime_env/py_modules.py``) shipped content-addressed like
    working_dir, joined to ``sys.path`` as import roots without chdir.
  - ``venv``: bool — hermetic interpreter isolation (the redesign of the
    reference's ``conda.py``/``container.py`` plugins for prebaked TPU
    images): the RAYLET creates a real virtualenv per env hash
    (``--system-site-packages`` so jax/the framework resolve from the
    image), installs ``pip`` deps into it, and spawns the worker WITH THAT
    INTERPRETER — user deps can shadow or pin versions without touching
    the node's site-packages, and `pip` state cannot leak across envs.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import zipfile
from typing import Any, Dict, List, Optional

RuntimeEnv = Dict[str, Any]

_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules", ".eggs"}
_MAX_WORKING_DIR_BYTES = 512 * 1024 * 1024
_KV_PREFIX = "@runtime_env/"


def _iter_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
        for name in sorted(filenames):
            yield os.path.join(dirpath, name)


def package_working_dir(path: str) -> bytes:
    """Deterministic zip of a directory (sorted entries, zeroed timestamps)
    so equal content yields an equal hash/URI."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for fpath in _iter_files(path):
            rel = os.path.relpath(fpath, path)
            total += os.path.getsize(fpath)
            if total > _MAX_WORKING_DIR_BYTES:
                raise ValueError(
                    f"working_dir {path!r} exceeds "
                    f"{_MAX_WORKING_DIR_BYTES >> 20} MB")
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(fpath).st_mode & 0xFFFF) << 16
            with open(fpath, "rb") as f:
                zf.writestr(info, f.read())
    return buf.getvalue()


def env_hash(env: RuntimeEnv) -> str:
    """Content hash identifying a prepared env (worker-pool cache key)."""
    return hashlib.sha1(
        json.dumps(env, sort_keys=True).encode()).hexdigest()[:16]


def prepare_runtime_env(env: Optional[RuntimeEnv], kv_put, kv_get) -> Optional[Dict]:
    """Driver-side normalization: upload working_dir once (content-addressed,
    skipped if the KV already holds the blob); returns the wire form
    {"working_dir_uri", "env_vars", "pip", "hash"} or None."""
    if not env:
        return None
    wire: Dict[str, Any] = {}
    wd = env.get("working_dir")
    if wd:
        if str(wd).startswith("gcs://"):
            wire["working_dir_uri"] = wd
        else:
            blob = package_working_dir(wd)
            digest = hashlib.sha1(blob).hexdigest()[:20]
            uri = f"gcs://{digest}"
            key = _KV_PREFIX + digest
            if kv_get(key) is None:
                kv_put(key, blob)
            wire["working_dir_uri"] = uri
    if env.get("env_vars"):
        vars_ = env["env_vars"]
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in vars_.items()):
            raise TypeError("env_vars must be Dict[str, str]")
        wire["env_vars"] = dict(vars_)
    if env.get("pip"):
        wire["pip"] = list(env["pip"])
    if env.get("venv"):
        wire["venv"] = True
    py_modules = env.get("py_modules")
    if py_modules:
        # Each entry is a local package dir (or a prior gcs:// URI); each is
        # uploaded content-addressed like working_dir but joins sys.path
        # WITHOUT chdir (reference: runtime_env/py_modules.py — modules are
        # import roots, working_dir is the cwd).
        uris = []
        for mod in py_modules:
            if str(mod).startswith("gcs://"):
                uris.append(mod)
                continue
            blob = package_working_dir(mod)
            digest = hashlib.sha1(blob).hexdigest()[:20]
            key = _KV_PREFIX + digest
            if kv_get(key) is None:
                kv_put(key, blob)
            # preserve the top-level package name: the zip holds the dir's
            # CONTENTS, so the import root must re-create <name>/
            uris.append(f"gcs://{digest}#{os.path.basename(os.path.abspath(mod))}")
        wire["py_modules_uris"] = uris
    unknown = set(env) - {"working_dir", "env_vars", "pip", "py_modules",
                          "venv"}
    if unknown:
        raise ValueError(f"unsupported runtime_env fields: {sorted(unknown)}")
    if not wire:
        return None
    wire["hash"] = env_hash(wire)
    return wire


def materialize(wire: Dict, kv_get, cache_root: str) -> None:
    """Worker-side: make the env live in THIS process before any user code
    runs — download/extract working_dir (content-addressed cache shared by
    workers on the node), chdir + sys.path it, install pip deps into a
    per-env site dir, export env_vars."""
    os.makedirs(cache_root, exist_ok=True)

    uri = wire.get("working_dir_uri")
    if uri:
        digest = uri[len("gcs://"):]
        target = os.path.join(cache_root, "working_dirs", digest)
        if not os.path.isdir(target):
            blob = kv_get(_KV_PREFIX + digest)
            if blob is None:
                raise RuntimeError(f"runtime_env blob {uri} not in GCS KV")
            tmp = target + f".tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)
            except OSError:  # another worker won the race
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        os.chdir(target)
        if target not in sys.path:
            sys.path.insert(0, target)

    for mod_uri in wire.get("py_modules_uris") or ():
        # "gcs://<digest>#<pkg_name>": the zip holds the package dir's
        # CONTENTS, so extraction recreates <root>/<pkg_name>/ and <root>
        # joins sys.path as the import root (no chdir — that's
        # working_dir's job).
        ref, _, pkg_name = mod_uri.partition("#")
        digest = ref[len("gcs://"):]
        root = os.path.join(cache_root, "py_modules", digest)
        if not os.path.isdir(root):
            blob = kv_get(_KV_PREFIX + digest)
            if blob is None:
                raise RuntimeError(f"runtime_env blob {ref} not in GCS KV")
            tmp = root + f".tmp.{os.getpid()}"
            dest = os.path.join(tmp, pkg_name) if pkg_name else tmp
            os.makedirs(dest, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(dest)
            try:
                os.rename(tmp, root)
            except OSError:  # another worker won the race
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        if root not in sys.path:
            sys.path.insert(0, root)

    pip_reqs = wire.get("pip")
    if pip_reqs and not wire.get("venv"):
        # (venv envs carry their deps IN the interpreter the raylet
        # launched this worker with — see ensure_venv)
        site = os.path.join(cache_root, "pip", wire["hash"])
        if not os.path.isdir(site):
            # install into a private tmp dir, then atomically rename — two
            # workers materializing the same env concurrently must never
            # write into one site dir (same pattern as working_dir above)
            tmp = site + f".tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            cmd = [sys.executable, "-m", "pip", "install",
                   "--target", tmp, "--no-warn-script-location"]
            if all(r.endswith(".whl") or os.path.exists(r) for r in pip_reqs):
                cmd.append("--no-index")  # local wheels: no network needed
            proc = subprocess.run(cmd + list(pip_reqs),
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install for runtime_env failed:\n{proc.stderr[-2000:]}")
            os.makedirs(os.path.dirname(site), exist_ok=True)
            try:
                os.rename(tmp, site)
            except OSError:  # another worker won the race
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        if site not in sys.path:
            sys.path.insert(0, site)

    for k, v in (wire.get("env_vars") or {}).items():
        os.environ[k] = v


def ensure_venv(wire: Dict, cache_root: str) -> str:
    """Raylet-side: create (or reuse) the hermetic virtualenv for a
    ``venv: True`` env and return its python executable. Keyed by the env
    hash; creation is atomic (private tmp dir, rename into place) so two
    concurrent spawns can't corrupt one env. The reference's analog is the
    agent materializing ``conda.py``/``container.py`` envs before worker
    launch and swapping ``context.py_executable``."""
    venv_dir = os.path.join(cache_root, "venvs", wire["hash"])
    py = os.path.join(venv_dir, "bin", "python")
    if os.path.exists(py):
        return py
    # Concurrent same-hash calls run in executor THREADS of the one
    # raylet process (spawn throttle allows several) — a pid-keyed tmp
    # dir does NOT separate them the way it does for materialize()'s
    # per-worker-process callers. Serialize creation PER ENV HASH and
    # re-check: distinct envs build concurrently (one slow pip install
    # must not make unrelated envs time out in the worker pool), while
    # same-hash spawns still create exactly once.
    with _venv_lock(wire["hash"]):
        if os.path.exists(py):
            return py
        return _create_venv(venv_dir, py, wire)


_VENV_LOCKS: Dict[str, Any] = {}
_VENV_LOCKS_GUARD = __import__("threading").Lock()


def _venv_lock(env_hash: str):
    with _VENV_LOCKS_GUARD:
        lock = _VENV_LOCKS.get(env_hash)
        if lock is None:
            lock = _VENV_LOCKS[env_hash] = __import__("threading").Lock()
        return lock


def _create_venv(venv_dir: str, py: str, wire: Dict) -> str:
    import uuid
    import venv as _venv

    tmp = venv_dir + f".tmp.{uuid.uuid4().hex[:8]}"
    # system-site-packages: jax/numpy/the framework come from the prebaked
    # image; the venv only OVERLAYS user deps
    _venv.create(tmp, system_site_packages=True, with_pip=True,
                 symlinks=True)
    # When THIS process itself runs inside a virtualenv (the common case:
    # the image ships /opt/venv), venv.create chains to the BASE
    # interpreter — system-site-packages then points at the base python's
    # site dir and the image's packages vanish. Propagate the creating
    # interpreter's site dirs with a .pth so the overlay always sees them.
    parent_sites = [p for p in sys.path
                    if p.endswith("site-packages") and os.path.isdir(p)]
    if parent_sites:
        import glob as _glob

        for site_dir in _glob.glob(os.path.join(tmp, "lib", "python*",
                                                "site-packages")):
            with open(os.path.join(site_dir, "_rt_parent_site.pth"),
                      "w") as f:
                f.write("\n".join(parent_sites) + "\n")
    reqs = wire.get("pip") or []
    if reqs:
        tmp_py = os.path.join(tmp, "bin", "python")
        cmd = [tmp_py, "-m", "pip", "install",
               "--no-warn-script-location"]
        if all(r.endswith(".whl") or os.path.exists(r) for r in reqs):
            cmd.append("--no-index")  # local wheels: no network needed
        proc = subprocess.run(cmd + list(reqs), capture_output=True,
                              text=True)
        if proc.returncode != 0:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"venv pip install failed:\n{proc.stderr[-2000:]}")
    os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
    try:
        os.rename(tmp, venv_dir)
    except OSError:  # another spawn won the race
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return py
