"""Per-task/actor/job runtime environments.

Reference analog: ``python/ray/_private/runtime_env/`` (architecture in its
``ARCHITECTURE.md``): ``working_dir.py`` + ``packaging.py`` (zip the project
dir into the GCS KV under a content-addressed ``gcs://`` URI, refcounted
node-local cache), ``pip.py`` (per-env python deps), worker-pool reuse keyed
by the env hash. Redesign: no separate per-node agent process — the worker
materializes its own env at startup (the raylet already spawns one worker
process per distinct env hash, so setup cost is paid once per (node, env)).
"""

from ray_tpu.runtime_env.runtime_env import (  # noqa: F401
    RuntimeEnv,
    env_hash,
    materialize,
    package_working_dir,
    prepare_runtime_env,
)
