"""The dashboard's browser UI: one self-contained HTML page.

Reference analog: ``dashboard/client/`` (a 183-file React SPA). Redesigned
for a zero-egress TPU pod: a single static page with no external assets,
rendered from the same ``/api/*`` REST endpoints the CLI uses (state
listings, jobs, serve apps, cluster resources, Prometheus text). Served at
``GET /`` by ``dashboard/head.py``.
"""

INDEX_HTML = r"""<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>ray_tpu dashboard</title>
<style>
:root {
  color-scheme: light;
  --surface-1: #fcfcfb; --surface-2: #f1f1ef;
  --text-primary: #0b0b0b; --text-secondary: #52514e; --text-muted: #7a7974;
  --border: #dddcd8;
  --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
  --good: #0ca30c; --warning: #fab219; --serious: #ec835a;
  --critical: #d03b3b;
}
@media (prefers-color-scheme: dark) {
  :root:not([data-theme="light"]) {
    color-scheme: dark;
    --surface-1: #1a1a19; --surface-2: #242423;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --text-muted: #8f8e86; --border: #3a3a38;
    --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
  }
}
:root[data-theme="dark"] {
  color-scheme: dark;
  --surface-1: #1a1a19; --surface-2: #242423;
  --text-primary: #ffffff; --text-secondary: #c3c2b7;
  --text-muted: #8f8e86; --border: #3a3a38;
  --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
}
* { box-sizing: border-box; }
body {
  margin: 0; background: var(--surface-1); color: var(--text-primary);
  font: 14px/1.45 system-ui, -apple-system, "Segoe UI", sans-serif;
}
header {
  display: flex; align-items: baseline; gap: 12px;
  padding: 14px 20px 10px;
  border-bottom: 1px solid var(--border);
}
header h1 { font-size: 17px; margin: 0; font-weight: 650; }
header .sub { color: var(--text-muted); font-size: 12px; }
header .spacer { flex: 1; }
header button {
  background: var(--surface-2); color: var(--text-secondary);
  border: 1px solid var(--border); border-radius: 6px;
  padding: 3px 10px; font-size: 12px; cursor: pointer;
}
.tiles {
  display: grid; grid-template-columns: repeat(auto-fit, minmax(150px, 1fr));
  gap: 10px; padding: 14px 20px;
}
.tile {
  background: var(--surface-2); border: 1px solid var(--border);
  border-radius: 8px; padding: 10px 14px;
}
.tile .label {
  font-size: 11px; letter-spacing: .04em; text-transform: uppercase;
  color: var(--text-muted);
}
.tile .value { font-size: 24px; font-weight: 650; margin-top: 2px;
  font-variant-numeric: tabular-nums; }
.tile .detail { font-size: 11px; color: var(--text-secondary); }
.muted { color: var(--text-muted); font-size: 12px; font-weight: 400; }
.meter {
  margin-top: 6px; height: 6px; border-radius: 4px;
  background: color-mix(in srgb, var(--border) 60%, var(--surface-2));
  overflow: hidden;
}
.meter > div {
  height: 100%; border-radius: 4px; background: var(--series-1);
  transition: width .4s;
}
/* step-breakdown stacked bar: compile/dispatch/device-sync share of one
   step's wall time; 2px surface gaps separate the fills. The tasks tab
   reuses the track for the per-phase task breakdown. */
.bk-track {
  display: flex; gap: 2px; width: 140px; height: 8px;
  border-radius: 4px; overflow: hidden;
  background: color-mix(in srgb, var(--border) 60%, var(--surface-2));
}
.bk-seg { height: 100%; border-radius: 2px; }
.bk-compile { background: var(--series-2); }
.bk-dispatch { background: var(--series-3); }
.bk-sync { background: var(--series-1); }
/* task phase colors: wait-ish phases warm, work-ish phases cool */
.ph-queue_wait { background: var(--warning); }
.ph-worker_acquire { background: var(--serious); }
.ph-execute { background: var(--series-1); }
.ph-arg_fetch { background: var(--series-3); }
.ph-result_store { background: var(--series-2); }
.ph-other { background: var(--text-muted); }
/* engine tick-phase bar: admission/prefill warm-ish, decode cool */
.phase-bar { display: flex; gap: 2px; height: 10px; margin: 6px 0 10px;
  max-width: 420px; }
.phase-bar .ph { display: inline-block; height: 100%; border-radius: 2px;
  background: var(--text-muted); }
.ph-admission { background: var(--warning); }
.ph-kv_restore { background: var(--series-3); }
.ph-prefill { background: var(--series-2); }
.ph-decode_step { background: var(--series-1); }
.ph-token_delivery { background: var(--serious); }
.ph-swap_barrier { background: var(--critical, #d33); }
.legend { display: flex; gap: 14px; margin: 0 0 10px;
  font-size: 12px; color: var(--text-secondary); }
.legend .chip { display: inline-block; width: 9px; height: 9px;
  border-radius: 2px; margin-right: 5px; }
nav { display: flex; gap: 2px; padding: 0 20px; flex-wrap: wrap;
  border-bottom: 1px solid var(--border); }
nav button {
  background: none; border: none; border-bottom: 2px solid transparent;
  color: var(--text-secondary); padding: 7px 12px; font-size: 13px;
  cursor: pointer;
}
nav button.active {
  color: var(--text-primary); border-bottom-color: var(--series-1);
  font-weight: 600;
}
main { padding: 14px 20px 40px; }
table { border-collapse: collapse; width: 100%; font-size: 13px; }
th {
  text-align: left; color: var(--text-muted); font-weight: 600;
  font-size: 11px; letter-spacing: .04em; text-transform: uppercase;
  padding: 6px 10px; border-bottom: 1px solid var(--border);
  position: sticky; top: 0; background: var(--surface-1);
}
td {
  padding: 6px 10px; border-bottom: 1px solid var(--border);
  color: var(--text-secondary); font-variant-numeric: tabular-nums;
  max-width: 380px; overflow: hidden; text-overflow: ellipsis;
  white-space: nowrap;
}
td.id { font-family: ui-monospace, monospace; font-size: 12px; }
.status { display: inline-flex; align-items: center; gap: 5px; }
.status .dot { width: 8px; height: 8px; border-radius: 50%; }
.s-good .dot { background: var(--good); }
.s-warning .dot { background: var(--warning); }
.s-serious .dot { background: var(--serious); }
.s-critical .dot { background: var(--critical); }
.s-muted .dot { background: var(--text-muted); }
.empty { color: var(--text-muted); padding: 24px 0; }
tr.clickable { cursor: pointer; }
tr.clickable:hover td { background: var(--surface-2); }
tr.detail td { background: var(--surface-2); }
table.kv { width: auto; margin: 6px 0; }
table.kv th { text-align: left; padding-right: 14px;
  color: var(--text-secondary); border: none; }
table.kv td { border: none; font-family: ui-monospace, monospace;
  font-size: 12px; }
.stack-btn {
  background: var(--surface-1); color: var(--text-secondary);
  border: 1px solid var(--border); border-radius: 6px;
  padding: 3px 10px; font-size: 12px; cursor: pointer; margin: 4px 0;
}
.stack-out { max-height: 300px; overflow: auto; font-size: 11px; }
.tl-head { color: var(--text-muted); font-size: 12px; margin: 4px 0 10px; }
.tl-row { display: flex; align-items: center; gap: 8px; height: 18px; }
.tl-label {
  width: 180px; flex: none; overflow: hidden; text-overflow: ellipsis;
  white-space: nowrap; font-size: 11px; color: var(--text-secondary);
  font-family: ui-monospace, monospace;
}
.tl-track {
  position: relative; flex: 1; height: 12px;
  background: var(--surface-2); border-radius: 3px; overflow: hidden;
}
.tl-bar { position: absolute; top: 0; height: 100%; border-radius: 2px;
  background: var(--series-1); opacity: .9; }
.tl-bar.s-good { background: var(--good); }
.tl-bar.s-critical { background: var(--critical); }
.tl-bar.s-warning { background: var(--warning); }
.tl-wait {
  position: absolute; top: 0; height: 100%;
  background: repeating-linear-gradient(45deg, transparent,
    transparent 3px, var(--border) 3px, var(--border) 5px);
}
#error { color: var(--critical); font-size: 12px; padding: 0 20px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="sub" id="version"></span>
  <span class="spacer"></span>
  <span class="sub" id="updated"></span>
  <button id="pause">pause</button>
  <button id="theme">theme</button>
</header>
<div class="tiles" id="tiles"></div>
<div id="error"></div>
<nav id="tabs"></nav>
<main id="content"></main>
<script>
"use strict";
const TABS = [
  {id: "nodes", label: "Nodes", url: "/api/nodes"},
  {id: "actors", label: "Actors", url: "/api/actors"},
  {id: "jobs", label: "Jobs", url: "/api/jobs"},
  {id: "placement_groups", label: "Placement groups",
   url: "/api/placement_groups"},
  {id: "tasks", label: "Tasks", url: "/api/tasks?limit=200"},
  {id: "errors", label: "Errors", url: "/api/errors?limit=200"},
  {id: "steps", label: "Steps", url: "/api/steps?limit=200"},
  {id: "timeline", label: "Timeline", url: "/api/tasks?limit=500"},
  {id: "objects", label: "Objects", url: "/api/objects?limit=200"},
  {id: "memory", label: "Memory", url: "/api/memory?limit=100"},
  {id: "logs", label: "Logs", url: "/api/logs?limit=300"},
  {id: "serve", label: "Serve", url: "/api/serve"},
  {id: "sched", label: "Scheduling", url: "/api/sched?limit=200"},
  {id: "engine", label: "Engine", url: "/api/engine"},
  {id: "rlhf", label: "RLHF", url: "/api/rlhf"},
  {id: "train", label: "Train", url: "/api/train"},
];
let active = "nodes", paused = false, data = {};

// --- status rendering: icon + label, never color alone ---
const STATUS_CLASS = {
  ALIVE: "s-good", RUNNING: "s-good", CREATED: "s-good",
  SUCCEEDED: "s-good", FINISHED: "s-good", COMMITTED: "s-good",
  HEALTHY: "s-good",
  PENDING: "s-warning", PENDING_CREATION: "s-warning",
  DEPLOYING: "s-warning", PREPARED: "s-warning", QUEUED: "s-warning",
  UPDATING: "s-warning",
  RESTARTING: "s-serious", RECONSTRUCTING: "s-serious",
  DEAD: "s-critical", FAILED: "s-critical", STOPPED: "s-critical",
  UNHEALTHY: "s-critical",
  // failure-plane categories (core/failure.py taxonomy)
  OOM_KILL: "s-critical", WORKER_CRASH: "s-critical",
  NODE_DEATH: "s-critical", ACTOR_RESTART_EXHAUSTED: "s-critical",
  OWNER_DIED: "s-critical", TASK_ERROR: "s-serious",
  OBJECT_LOST: "s-serious", RUNTIME_ENV_SETUP: "s-serious",
  GET_TIMEOUT: "s-warning", SCHEDULING_TIMEOUT: "s-warning",
  PG_REMOVED: "s-warning", CANCELLED: "s-muted",
};
function esc(s) {
  return String(s ?? "").replace(/[&<>"]/g,
    c => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c]));
}
function statusCell(s) {
  const cls = STATUS_CLASS[String(s).toUpperCase()] || "s-muted";
  return `<span class="status ${cls}"><span class="dot"></span>` +
         `${esc(s)}</span>`;
}
function fmtRes(r) {
  if (!r || typeof r !== "object") return "";
  return Object.entries(r).map(([k, v]) => `${esc(k)}:${esc(v)}`)
    .join(" ");
}

// --- per-tab table definitions: [header, row -> cell html] ---
const COLS = {
  nodes: [
    ["Node", r => `<td class="id">${esc(r.node_id)}</td>`],
    ["Address", r => `<td>${esc(r.address || "")}</td>`],
    ["State", r => `<td>${statusCell(r.alive === false ? "DEAD"
                                     : "ALIVE")}</td>`],
    ["Labels", r => `<td>${fmtRes(r.labels)}</td>`],
    ["Total", r => `<td>${fmtRes(r.resources_total || r.resources)}</td>`],
    ["Available", r => `<td>${fmtRes(r.resources_available
                                     || r.available)}</td>`],
    ["Queued", r => `<td>${esc(r.queue_depth ?? "")}</td>`],
    ["Classes", r => `<td>${((r.sched || {}).classes || [])
      .slice(0, 3)
      .map(c => `${esc(c["class"])}:${esc(c.depth)}` +
           (c.wait_p99_s != null ? ` (p99 ${esc(c.wait_p99_s)}s)` : ""))
      .join(" ")}</td>`],
    ["Warm pool", r => { const w = (r.sched || {}).warm || {};
      const served = (w.warm_hits || 0) + (w.cold_spawns || 0);
      if (!served && !w.idle && !w.floor) return "<td></td>";
      const rate = served
        ? ` hit ${Math.round(100 * (w.warm_hits || 0) / served)}%` : "";
      return `<td>${esc(w.idle ?? 0)} idle / floor ${esc(w.floor ?? 0)}` +
             `${rate}</td>`; }],
  ],
  actors: [
    ["Actor", r => `<td class="id">${esc(r.actor_id)}</td>`],
    ["Name", r => `<td>${esc(r.name || "")}</td>`],
    ["Class", r => `<td>${esc(r.class_name || "")}</td>`],
    ["State", r => `<td>${statusCell(r.state)}</td>`],
    ["Node", r => `<td class="id">${esc(r.node_id || "")}</td>`],
    ["Restarts", r => `<td>${esc(r.num_restarts ?? 0)}</td>`],
  ],
  jobs: [
    ["Job", r => `<td class="id">${esc(r.job_id || r.submission_id)}</td>`],
    ["Entrypoint", r => `<td>${esc(r.entrypoint || "")}</td>`],
    ["Status", r => `<td>${statusCell(r.status)}</td>`],
    ["Message", r => `<td>${esc(r.message || "")}</td>`],
  ],
  placement_groups: [
    ["Group", r => `<td class="id">${esc(r.pg_id)}</td>`],
    ["Name", r => `<td>${esc(r.name || "")}</td>`],
    ["Strategy", r => `<td>${esc(r.strategy || "")}</td>`],
    ["State", r => `<td>${statusCell(r.state)}</td>`],
    ["Bundles", r => `<td>${esc((r.bundles || []).length)}</td>`],
  ],
  tasks: [
    ["Task", r => `<td class="id">${esc(r.task_id)}</td>`],
    ["Name", r => `<td>${esc(r.name || r.func_name || "")}</td>`],
    ["State", r => `<td>${statusCell(r.state || r.status)}</td>`],
    ["Node", r => `<td class="id">${esc(r.node_id || "")}</td>`],
    ["Duration", r => {
      const t = r.times || {};
      const end = t.FINISHED || t.FAILED, start = t.RUNNING || t.PENDING;
      return `<td>${end && start
        ? ((end - start).toFixed(2) + "s") : ""}</td>`;
    }],
    ["Queue ms", r => `<td>${r.phases
      ? ms(r.phases.queue_wait) : ""}</td>`],
    ["Phases", r => `<td>${phaseBar(r)}</td>`],
  ],
  objects: [
    ["Object", r => `<td class="id">${esc(r.object_id)}</td>`],
    ["Size", r => `<td>${esc(r.size ?? "")}</td>`],
    ["Locations", r => `<td class="id">${esc(
      (r.locations || []).join(" "))}</td>`],
  ],
  // failure plane: the categorized FailureEvent feed (/api/errors)
  errors: [
    ["When", r => `<td>${esc(new Date(1000 * (r.last_t || r.t || 0))
      .toLocaleTimeString())}</td>`],
    ["Category", r => `<td>${statusCell(r.category || "unknown")}</td>`],
    ["Node", r => `<td class="id">${esc(
      String(r.node_id || "").slice(0, 8))}</td>`],
    ["What", r => `<td class="id">${esc(r.name || r.task_id
      || r.actor_id || r.worker_id || "")}</td>`],
    ["Count", r => `<td>${esc(r.count ?? 1)}</td>`],
    ["Message", r => `<td>${esc(r.message || "")}</td>`],
  ],
  steps: [
    ["Kind", r => `<td>${esc(prof(r).kind || "")}</td>`],
    ["Name", r => `<td>${esc(prof(r).name || "")}</td>`],
    ["Step", r => `<td>${esc(prof(r).step ?? "")}</td>`],
    ["Wall ms", r => `<td>${ms(prof(r).wall_s)}</td>`],
    ["Compile ms", r => `<td>${ms(prof(r).compile_s)}</td>`],
    ["Dispatch ms", r => `<td>${ms(prof(r).dispatch_s)}</td>`],
    ["Sync ms", r => `<td>${ms(prof(r).execute_s)}</td>`],
    ["Tok/s", r => `<td>${prof(r).tokens_per_s
      ? prof(r).tokens_per_s.toFixed(1) : ""}</td>`],
    ["MFU", r => `<td>${prof(r).mfu
      ? (100 * prof(r).mfu).toFixed(2) + "%" : ""}</td>`],
    ["Breakdown", r => `<td>${breakdownBar(prof(r))}</td>`],
  ],
};
function prof(r) { return r.profile || {}; }
function ms(v) { return v == null ? "" : (1000 * v).toFixed(2); }
function breakdownBar(p) {
  const wall = p.wall_s || 0;
  if (!wall) return "";
  const seg = (cls, v, label) => {
    const pct = Math.max(0, Math.min(100, 100 * (v || 0) / wall));
    return pct < 0.5 ? "" :
      `<div class="bk-seg ${cls}" style="width:${pct.toFixed(1)}%"` +
      ` title="${esc(label)} ${ms(v)}ms"></div>`;
  };
  return `<div class="bk-track">` +
    seg("bk-compile", p.compile_s, "compile") +
    seg("bk-dispatch", p.dispatch_s, "dispatch") +
    seg("bk-sync", p.execute_s, "device sync") + `</div>`;
}
const STEP_LEGEND = `<div class="legend">` +
  `<span><span class="chip bk-compile"></span>compile</span>` +
  `<span><span class="chip bk-dispatch"></span>dispatch</span>` +
  `<span><span class="chip bk-sync"></span>device sync</span></div>`;

// task-lifecycle phase drill-down (traced tasks; util/tracing.PHASE_ORDER)
const PHASE_ORDER = ["submit", "queue_wait", "spillback", "worker_acquire",
  "transfer", "arg_fetch", "execute", "result_store", "driver_get"];
const PHASE_CLASS = {queue_wait: "ph-queue_wait",
  worker_acquire: "ph-worker_acquire", execute: "ph-execute",
  arg_fetch: "ph-arg_fetch", result_store: "ph-result_store"};
function phaseBar(r) {
  const p = r.phases;
  if (!p) return "";
  const keys = PHASE_ORDER.filter(k => p[k] > 0)
    .concat(Object.keys(p).filter(k => !PHASE_ORDER.includes(k)));
  const total = keys.reduce((a, k) => a + (p[k] || 0), 0);
  if (!total) return "";
  const segs = keys.map(k => {
    const pct = Math.max(0, Math.min(100, 100 * p[k] / total));
    const src = k === "worker_acquire" && r.worker_source
      ? ` (${r.worker_source})` : "";
    return pct < 0.5 ? "" :
      `<div class="bk-seg ${PHASE_CLASS[k] || "ph-other"}"` +
      ` style="width:${pct.toFixed(1)}%"` +
      ` title="${esc(k)}${esc(src)} ${ms(p[k])}ms"></div>`;
  });
  return `<div class="bk-track">${segs.join("")}</div>`;
}
const PHASE_LEGEND = `<div class="legend">` +
  `<span><span class="chip ph-queue_wait"></span>queue wait</span>` +
  `<span><span class="chip ph-worker_acquire"></span>worker acquire</span>` +
  `<span><span class="chip ph-arg_fetch"></span>arg fetch</span>` +
  `<span><span class="chip ph-execute"></span>execute</span>` +
  `<span><span class="chip ph-result_store"></span>result store</span>` +
  `<span><span class="chip ph-other"></span>other</span></div>`;

function renderTiles() {
  const res = data.resources || {};
  const total = res.total || {}, avail = res.available || {};
  const nodes = data.nodes || [], actors = data.actors || [];
  const jobs = data.jobs || [];
  const tiles = [];
  const aliveN = nodes.filter(n => n.alive !== false).length;
  tiles.push(tile("Nodes", `${aliveN}`,
    nodes.length > aliveN ? `${nodes.length - aliveN} dead` : "alive"));
  const aliveA = actors.filter(a =>
    String(a.state).toUpperCase() === "ALIVE").length;
  tiles.push(tile("Actors", `${aliveA}`, `${actors.length} total`));
  const runJ = jobs.filter(j =>
    ["RUNNING", "PENDING"].includes(String(j.status).toUpperCase())).length;
  tiles.push(tile("Jobs", `${runJ}`, `${jobs.length} total`));
  for (const key of ["CPU", "TPU"]) {
    if (!(key in total)) continue;
    const t = total[key] || 0, a = avail[key] ?? t;
    const used = Math.max(0, t - a);
    const pct = t ? Math.round(100 * used / t) : 0;
    tiles.push(tile(`${key} in use`, `${used}/${t}`,
      `${pct}%`, pct));
  }
  document.getElementById("tiles").innerHTML = tiles.join("");
}
function tile(label, value, detail, meterPct) {
  const meter = meterPct === undefined ? "" :
    `<div class="meter"><div style="width:${meterPct}%"></div></div>`;
  return `<div class="tile"><div class="label">${esc(label)}</div>` +
    `<div class="value">${esc(value)}</div>` +
    `<div class="detail">${esc(detail)}</div>${meter}</div>`;
}

// --- task timeline: horizontal bars over the shared time window ---
function renderTimeline(el) {
  const rows = (data.timeline || []).filter(r => r.times
    && (r.times.RUNNING || r.times.PENDING));
  if (!rows.length) {
    el.innerHTML = `<div class="empty">no task events yet</div>`;
    return;
  }
  const now = Date.now() / 1000;
  const start = Math.min(...rows.map(r =>
    r.times.PENDING || r.times.RUNNING));
  const end = Math.max(now, ...rows.map(r =>
    r.times.FINISHED || r.times.FAILED || now));
  const span = Math.max(0.001, end - start);
  const pct = t => (100 * (t - start) / span).toFixed(2);
  const byNode = {};
  for (const r of rows) {
    (byNode[r.node_id || "(unscheduled)"] ??= []).push(r);
  }
  const lane = r => {
    const t = r.times;
    const s = t.RUNNING || t.PENDING;
    const e = t.FINISHED || t.FAILED || now;
    const state = String(r.state || r.status || "").toUpperCase();
    const cls = STATUS_CLASS[state] || "s-muted";
    const wait = t.RUNNING && t.PENDING
      ? `<div class="tl-wait" style="left:${pct(t.PENDING)}%;` +
        `width:${Math.max(0.2, pct(t.RUNNING) - pct(t.PENDING))}%"></div>`
      : "";
    return `<div class="tl-row" title="${esc(r.name || r.func_name || "")}` +
      ` ${esc(state)} ${(e - s).toFixed(2)}s">` +
      `<span class="tl-label">${esc(r.name || r.func_name || r.task_id)}` +
      `</span><div class="tl-track">${wait}` +
      `<div class="tl-bar ${cls}" style="left:${pct(s)}%;` +
      `width:${Math.max(0.3, pct(e) - pct(s))}%"></div></div></div>`;
  };
  el.innerHTML = `<div class="tl-head">window ${span.toFixed(1)}s ` +
    `(${rows.length} tasks; hatched = queued wait)</div>` +
    Object.entries(byNode).map(([n, rs]) =>
      `<h3 class="id">${esc(n)}</h3>` +
      rs.sort((a, b) => (a.times.PENDING || a.times.RUNNING || 0)
                      - (b.times.PENDING || b.times.RUNNING || 0))
        .map(lane).join("")).join("");
}

// --- per-actor drill-down: expandable full record + live stack ---
let openActor = null;
function actorDetail(r) {
  const rows = Object.entries(r).map(([k, v]) =>
    `<tr><th>${esc(k)}</th><td>${esc(
       typeof v === "object" ? JSON.stringify(v) : v)}</td></tr>`);
  return `<tr class="detail"><td colspan="6"><table class="kv">` +
    rows.join("") + `</table>` +
    `<button class="stack-btn" data-node="${esc(r.node_id || "")}">` +
    `fetch live stacks on this node</button>` +
    `<pre class="stack-out" id="stack-out"></pre></td></tr>`;
}
async function fetchStacks(nodeId) {
  const out = document.getElementById("stack-out");
  out.textContent = "collecting…";
  try {
    const d = await fetchJson(
      `/api/stacks?node_id=${encodeURIComponent(nodeId)}&timeout=3`);
    out.textContent = JSON.stringify(d, null, 2);
  } catch (e) { out.textContent = String(e); }
}

// --- memory tab: store usage by node + owner ledger + OOM post-mortems ---
function fmtBytes(n) {
  if (n == null || n < 0) return "?";
  const units = ["B", "KiB", "MiB", "GiB", "TiB"];
  let i = 0;
  while (Math.abs(n) >= 1024 && i < units.length - 1) { n /= 1024; i++; }
  return `${n.toFixed(i ? 1 : 0)} ${units[i]}`;
}
function shortOid(oid) {
  oid = String(oid || "");
  return oid.length <= 18 ? oid
    : `${oid.slice(0, 8)}..${oid.slice(-8)}`;
}
function renderMemory(el) {
  const snap = data.memory || {};
  const nodes = snap.nodes || [];
  if (!nodes.length) {
    el.innerHTML = `<div class="empty">no memory reports yet</div>`;
    return;
  }
  const nodeRows = nodes.map(n => {
    if (n.error) return `<tr><td class="id">${esc(n.node_id)}</td>` +
      `<td colspan="8">${esc(n.error)}</td></tr>`;
    const s = n.store || {};
    return `<tr><td class="id">${esc((n.node_id || "").slice(0, 8))}</td>` +
      `<td>${fmtBytes(s.used_bytes)}</td>` +
      `<td>${fmtBytes(s.capacity_bytes)}</td>` +
      `<td>${fmtBytes(s.in_mem_bytes)}</td>` +
      `<td>${fmtBytes(s.spilled_bytes)} (${esc(s.spilled_count ?? 0)})</td>` +
      `<td>${esc(s.pinned_count ?? 0)}</td>` +
      `<td>${esc(s.num_objects ?? 0)}</td>` +
      `<td>${esc(s.spills ?? 0)}/${esc(s.restores ?? 0)}</td>` +
      `<td>${esc(s.oom_kills ?? 0)}/${esc(s.pin_purges ?? 0)}</td></tr>`;
  }).join("");
  const objs = nodes.flatMap(n => (n.objects || []).map(o =>
    ({...o, node: (n.node_id || "").slice(0, 8)})))
    .sort((a, b) => (b.size || 0) - (a.size || 0)).slice(0, 30);
  const objRows = objs.map(o =>
    `<tr><td class="id">${esc(o.node)}</td>` +
    `<td class="id">${esc(shortOid(o.oid))}</td>` +
    `<td>${fmtBytes(o.size)}</td><td>${statusCell(o.state)}</td>` +
    `<td>${(o.age_s ?? 0).toFixed(1)}s</td>` +
    `<td class="id">${esc(o.owner || "")}</td>` +
    `<td>${esc(o.call_site || "")}</td></tr>`).join("");
  const suspects = (snap.leak_suspects || []).map(o =>
    `<tr><td class="id">${esc(shortOid(o.oid))}</td>` +
    `<td>${fmtBytes(o.size)}</td><td>${esc(o.local_refs ?? "")}</td>` +
    `<td>${(o.age_s ?? 0).toFixed(0)}s</td>` +
    `<td>${esc(o.call_site || "")}</td></tr>`).join("");
  const ooms = (snap.oom_kills || []).map(ev => {
    const v = ev.victim || {}, m = ev.node_memory || {};
    return `<tr><td>${esc(new Date(1000 * (ev.t || 0))
        .toLocaleTimeString())}</td>` +
      `<td class="id">${esc((ev.node_id || "").slice(0, 8))}</td>` +
      `<td>${esc(v.role || "")} ${esc((v.worker_id || "").slice(0, 8))}` +
      `</td><td>${fmtBytes(v.rss)}</td>` +
      `<td>${esc(v.task || v.actor_id || "(idle)")}</td>` +
      `<td>${fmtBytes(m.used)} / ${fmtBytes(m.total)}</td></tr>`;
  }).join("");
  el.innerHTML =
    `<h3>Object store by node</h3><table><tr><th>Node</th>` +
    `<th>Shm used</th><th>Capacity</th><th>In-mem</th><th>Spilled</th>` +
    `<th>Pins</th><th>Objects</th><th>Spills/restores</th>` +
    `<th>OOM/pin-purges</th></tr>${nodeRows}</table>` +
    `<h3>Largest objects</h3>` +
    (objs.length ? `<table><tr><th>Node</th><th>Object</th><th>Size</th>` +
      `<th>State</th><th>Age</th><th>Owner</th><th>Call site</th></tr>` +
      `${objRows}</table>` : `<div class="empty">store empty</div>`) +
    `<h3>Leak suspects</h3>` +
    (suspects ? `<table><tr><th>Object</th><th>Size</th>` +
      `<th>Local refs</th><th>Age</th><th>Call site</th></tr>` +
      `${suspects}</table>` : `<div class="empty">none</div>`) +
    `<h3>OOM kills</h3>` +
    (ooms ? `<table><tr><th>When</th><th>Node</th><th>Victim</th>` +
      `<th>RSS</th><th>Running</th><th>Node memory</th></tr>` +
      `${ooms}</table>` : `<div class="empty">none recorded</div>`);
}

// --- logs tab: the raylets' worker-log rings ---
function renderLogs(el) {
  const rows = data.logs || [];
  if (!rows.length) {
    el.innerHTML = `<div class="empty">no worker log lines yet</div>`;
    return;
  }
  el.innerHTML = `<div class="tl-head">${rows.length} line(s) — filter ` +
    `with /api/logs?node=&amp;worker=</div>` +
    `<pre class="stack-out" style="max-height:70vh">` +
    rows.map(e => `${esc((e.node_id || "").slice(0, 8))} ` +
      `${esc((e.worker_id || "").slice(0, 8))} ${esc(e.line)}`)
      .join("\n") + `</pre>`;
}

// --- scheduling tab: placement decision receipts + cross-node balance ---
function renderSched(el) {
  const payload = data.sched || {};
  const bal = payload.balance || {};
  const nodes = bal.nodes || [];
  const maxLoad = Math.max(1, ...nodes.map(n => n.load || 0));
  const bars = nodes.map(n =>
    `<tr><td class="id">${esc((n.node_id || "").slice(0, 8))}</td>` +
    `<td>${esc(n.queued ?? 0)}</td><td>${esc(n.running ?? 0)}</td>` +
    `<td style="min-width:180px"><div class="meter"><div ` +
    `style="width:${Math.round(100 * (n.load || 0) / maxLoad)}%">` +
    `</div></div></td><td>${esc(n.load ?? 0)}</td></tr>`).join("");
  const rows = (payload.decisions || []).slice().reverse().map(d => {
    const when = d.last_t || d.t
      ? new Date(1000 * (d.last_t || d.t)).toLocaleTimeString() : "";
    const who = d.name || d.task_id || d.actor_id || d.pg_id || "";
    const hop = d.kind === "spillback"
      ? `${esc(String(d.from_node || "").slice(0, 8))} &rarr; ` +
        `${esc(String(d.node_id || "").slice(0, 8))} ` +
        `(hops ${esc(d.hops ?? 1)})` : "";
    return `<tr><td>${esc(when)}</td>` +
      `<td>${esc(d.kind || "")}</td>` +
      `<td class="id">${esc(String(d.node_id || "").slice(0, 8))}</td>` +
      `<td>${esc(d.reason || "")}</td>` +
      `<td class="id">${esc(String(who).slice(0, 16))}</td>` +
      `<td>${esc(d.count ?? 1)}</td><td>${hop}</td>` +
      `<td>${esc((d.candidates || []).length)}</td></tr>`;
  }).join("");
  const cov = typeof bal.cov === "number" ? bal.cov.toFixed(3) : "?";
  el.innerHTML =
    `<h3>Cross-node balance <span class="muted">load CoV ${esc(cov)}` +
    `</span></h3>` +
    (nodes.length ? `<table><tr><th>Node</th><th>Queued</th>` +
      `<th>Running</th><th>Load</th><th></th></tr>${bars}</table>`
      : `<div class="empty">no balance samples yet</div>`) +
    `<h3>Placement decisions</h3>` +
    (rows ? `<table><tr><th>When</th><th>Kind</th><th>Node</th>` +
      `<th>Reason</th><th>What</th><th>Count</th><th>Hop</th>` +
      `<th>Candidates</th></tr>${rows}</table>`
      : `<div class="empty">none recorded</div>`);
}

// --- engine tab: ContinuousEngine flight-recorder snapshots ---
const ENGINE_PHASES = ["admission", "kv_restore", "prefill", "decode_step",
                       "token_delivery", "swap_barrier"];
function renderEngine(el) {
  const payload = data.engine || {};
  const engines = payload.engines || [];
  if (!engines.length) {
    el.innerHTML = `<div class="empty">no engine flight-recorder ` +
      `snapshots — start a ContinuousEngine (RT_ENGINE_RECORDER=1)</div>`;
    return;
  }
  const ms = v => v == null ? "" : (1e3 * v).toFixed(1);
  el.innerHTML = engines.map(snap => {
    const s = snap.summary || {};
    const phases = s.phase_s || {};
    const wall = Math.max(1e-9, s.tick_wall_s || 0);
    const bar = ENGINE_PHASES.filter(p => phases[p] > 0).map(p =>
      `<span class="ph ph-${esc(p)}" title="${esc(p)} ` +
      `${(100 * phases[p] / wall).toFixed(1)}%" style="width:` +
      `${Math.max(1, Math.round(100 * phases[p] / wall))}px"></span>`)
      .join("");
    const att = (label, v, p99, tgt) => v == null ? "" :
      `${label} ${(100 * v).toFixed(1)}%` +
      (p99 != null ? ` (p99 ${ms(p99)}ms / tgt ${ms(tgt)}ms)` : "");
    const reqs = (snap.requests || []).slice().reverse().map(r =>
      `<tr><td class="id">${esc(String(r.request_id ?? r.rid ?? "")
        .slice(0, 16))}</td>` +
      `<td>${statusCell(String(r.state || "").toUpperCase())}</td>` +
      `<td>${esc(r.queue_wait_ms ?? "")}</td>` +
      `<td>${esc(r.prompt_tokens ?? 0)}/${esc(r.cached_tokens ?? 0)}</td>` +
      `<td>${esc(r.tokens ?? 0)}</td><td>${esc(r.decode_ticks ?? 0)}</td>` +
      `<td>${esc(r.ttft_ms ?? "")}</td><td>${esc(r.tpot_ms ?? "")}</td>` +
      `</tr>`).join("");
    return `<h3>${esc(snap.name || "engine")} <span class="muted">` +
      `${esc(String(snap.node || "").slice(0, 8))}:${esc(snap.pid || "")}` +
      `</span></h3>` +
      `<div class="muted">ticks ${esc(s.window_ticks ?? 0)} · active ` +
      `${esc(s.active ?? 0)}/${esc(s.max_slots ?? "?")} · ` +
      `${att("TTFT", s.ttft_attainment, s.ttft_p99_s, s.ttft_slo_s)} · ` +
      `${att("TPOT", s.tpot_attainment, s.tpot_p99_s, s.tpot_slo_s)} · ` +
      `goodput ${(s.goodput_tok_s || 0).toFixed(1)} tok/s ` +
      `(capacity ${(s.capacity_tok_s || 0).toFixed(1)}) · ` +
      `decode-eff ${((s.decode_efficiency || 0) * 100).toFixed(1)}% · ` +
      `gap p99 ${ms(s.tick_gap_p99_s)}ms · overhead ` +
      `${((s.overhead_frac || 0) * 100).toFixed(3)}%</div>` +
      `<div class="phase-bar">${bar}</div>` +
      (reqs ? `<table><tr><th>Request</th><th>State</th>` +
        `<th>Queue ms</th><th>Prompt/cached</th><th>Tokens</th>` +
        `<th>Ticks</th><th>TTFT ms</th><th>TPOT ms</th></tr>${reqs}` +
        `</table>` : `<div class="empty">no request records yet</div>`);
  }).join("");
}

// --- rlhf tab: RLHFPipeline flight-recorder snapshots ---
const RLHF_ROLES = ["generator", "reference", "reward", "learner"];
function renderRlhf(el) {
  const payload = data.rlhf || {};
  const pipes = payload.pipelines || [];
  if (!pipes.length) {
    el.innerHTML = `<div class="empty">no RLHF flight-recorder ` +
      `snapshots — run an RLHFPipeline (RT_RLHF_RECORDER=1)</div>`;
    return;
  }
  const pct = v => v == null ? "" : (100 * v).toFixed(1) + "%";
  el.innerHTML = pipes.map(snap => {
    const s = snap.summary || {};
    const stale = s.staleness || {};
    const busy = s.role_busy_frac || {};
    const idle = s.role_idle_frac || {};
    const roles = RLHF_ROLES.filter(r => r in busy || r in idle).map(r =>
      `<tr><td>${esc(r)}</td><td>${pct(busy[r])}</td>` +
      `<td>${pct(idle[r])}</td></tr>`).join("");
    const rc = s.receipt_last || {};
    const receipt = Object.keys(rc).length ?
      `<div class="muted">last shipment v${esc(rc.version ?? "?")} · ` +
      `${((rc.nbytes || 0) / 1e6).toFixed(1)}MB/${esc(rc.n_leaves ?? 0)} ` +
      `leaves · pump ${((rc.pump_wall_s || 0) * 1e3).toFixed(1)}ms · ` +
      `fetch ${((rc.fetch_wall_s || 0) * 1e3).toFixed(1)}ms · barrier ` +
      `${((rc.barrier_drain_s || 0) * 1e3).toFixed(1)}ms · swap ` +
      `${((rc.swap_apply_s || 0) * 1e3).toFixed(1)}ms</div>` : "";
    const iters = (snap.iterations || []).slice().reverse().map(r =>
      r.state === "interrupted" ?
        `<tr><td>${esc(r.seq ?? "")}</td>` +
        `<td>${statusCell("FAILED")}</td>` +
        `<td colspan="5">interrupted in ${esc(r.phase || "?")} ` +
        `${esc(String(r.error || "").slice(0, 60))}</td></tr>` :
        `<tr><td>${esc(r.iteration ?? r.seq ?? "")}</td>` +
        `<td>${statusCell("FINISHED")}</td>` +
        `<td>${esc(r.wall_ms ?? "")}</td><td>${pct(r.bubble_fraction)}</td>` +
        `<td>${pct(r.coverage)}</td><td>${esc(r.staleness ?? 0)}</td>` +
        `<td>${esc(r.tokens ?? 0)}</td></tr>`).join("");
    return `<h3>${esc(snap.name || "rlhf")} <span class="muted">` +
      `${esc(String(snap.node || "").slice(0, 8))}:${esc(snap.pid || "")}` +
      `</span></h3>` +
      `<div class="muted">iterations ${esc(s.iterations_total ?? 0)} ` +
      `(${esc(s.interrupted_total ?? 0)} interrupted) · bubble ` +
      `${pct(s.bubble_fraction)} (last ${pct(s.bubble_last)}) · coverage ` +
      `${pct(s.coverage)} · staleness p99 ${esc(stale.p99 ?? 0)} ` +
      `(max ${esc(stale.max ?? 0)}) · overhead ` +
      `${((s.overhead_frac || 0) * 100).toFixed(3)}%</div>` +
      (roles ? `<table><tr><th>Role</th><th>Busy</th><th>Idle</th></tr>` +
        `${roles}</table>` : "") + receipt +
      (iters ? `<table><tr><th>Iter</th><th>State</th><th>Wall ms</th>` +
        `<th>Bubble</th><th>Coverage</th><th>Staleness</th><th>Tokens</th>` +
        `</tr>${iters}</table>` :
        `<div class="empty">no iteration records yet</div>`);
  }).join("");
}

// --- train tab: StepDriver flight-recorder snapshots ---
function renderTrain(el) {
  const payload = data.train || {};
  const drivers = payload.drivers || [];
  if (!drivers.length) {
    el.innerHTML = `<div class="empty">no train flight-recorder ` +
      `snapshots — run a fused StepDriver (RT_TRAIN_RECORDER=1)</div>`;
    return;
  }
  const pct = v => v == null ? "" : (100 * v).toFixed(1) + "%";
  const mfu = v => v == null ? "" : v.toFixed(4);
  el.innerHTML = drivers.map(snap => {
    const s = snap.summary || {};
    const wf = s.waterfall || {};
    const cost = wf.mfu_cost || {};
    const buckets = Object.entries(cost).filter(([, v]) => v > 0).map(
      ([b, v]) => `<tr><td>${esc(b)}</td>` +
        `<td>${esc(((wf.buckets_s || {})[b] ?? wf.uncovered_s ?? 0)
          .toFixed(3))}s</td><td>${mfu(v)}</td></tr>`).join("");
    const launches = (snap.launches || []).slice().reverse().map(r => {
      const pm = r.phases_ms || {};
      return `<tr><td>${esc(r.seq ?? "")}</td>` +
        `<td>${statusCell(r.done ? "FINISHED" : "RUNNING")}</td>` +
        `<td>${esc(r.k ?? "")}</td>` +
        `<td>${esc((r.wall_ms ?? 0).toFixed(1))}</td>` +
        `<td>${esc((pm.data_wait ?? 0).toFixed(1))}</td>` +
        `<td>${esc((pm.dispatch ?? 0).toFixed(1))}</td>` +
        `<td>${esc((pm.device_compute ?? 0).toFixed(1))}</td>` +
        `<td>${esc((pm.host_tax ?? 0).toFixed(1))}</td>` +
        `<td>${r.gap_ms != null ? esc(r.gap_ms.toFixed(1)) : ""}</td>` +
        `<td>${esc(r.tokens ?? 0)}</td></tr>`;
    }).join("");
    return `<h3>${esc(snap.name || "train")} <span class="muted">` +
      `${esc(String(snap.node || "").slice(0, 8))}:${esc(snap.pid || "")}` +
      `</span></h3>` +
      `<div class="muted">launches ${esc(s.launches_total ?? 0)} ` +
      `(${esc(s.compiles ?? 0)} compiled) · steps ` +
      `${esc(s.steps_total ?? 0)} · ${esc(s.tokens_per_s ?? 0)} tok/s · ` +
      `phase coverage ${pct(s.phase_sum_ratio)} · gap p99 ` +
      `${((s.launch_gap_p99_s || 0) * 1e3).toFixed(1)}ms · data_wait ` +
      `${pct(s.data_wait_frac)} · overhead ` +
      `${((s.overhead_frac || 0) * 100).toFixed(3)}%</div>` +
      (wf.raw_mfu != null ?
        `<div class="muted">MFU waterfall: raw ${mfu(wf.raw_mfu)} → ` +
        `achieved ${mfu(wf.achieved_mfu)} (gap ${pct(s.mfu_gap_frac)}, ` +
        `marginal ${mfu(s.marginal_mfu)})</div>` : "") +
      (buckets ? `<table><tr><th>Lost to</th><th>Wall</th>` +
        `<th>MFU cost</th></tr>${buckets}</table>` : "") +
      (launches ? `<table><tr><th>Launch</th><th>State</th><th>K</th>` +
        `<th>Wall ms</th><th>Data ms</th><th>Dispatch ms</th>` +
        `<th>Device ms</th><th>Host-tax ms</th><th>Gap ms</th>` +
        `<th>Tokens</th></tr>${launches}</table>` :
        `<div class="empty">no launch records yet</div>`);
  }).join("");
}

function renderTable() {
  const el = document.getElementById("content");
  if (active === "timeline") { renderTimeline(el); return; }
  if (active === "memory") { renderMemory(el); return; }
  if (active === "logs") { renderLogs(el); return; }
  if (active === "sched") { renderSched(el); return; }
  if (active === "engine") { renderEngine(el); return; }
  if (active === "rlhf") { renderRlhf(el); return; }
  if (active === "train") { renderTrain(el); return; }
  if (active === "serve") {
    const payload = data.serve || {};
    const apps = payload.applications || payload;
    const decisions = payload.decisions || [];
    const proxies = payload.proxies || [];
    const names = Object.keys(apps);
    const ms = v => v ? (1e3 * v).toFixed(1) : "0.0";
    el.innerHTML = (proxies.length > 1 ?
      `<div class="muted">proxies: ` + proxies.map(p =>
        `${esc(p.proxy)}:${esc(p.port)}`).join(", ") + `</div>` : "") +
    (names.length ? "" :
      `<div class="empty">no serve applications</div>`) + names.map(n => {
      const app = apps[n] || {};
      const deps = app.deployments || app;
      return `<h3>${esc(n)} ${statusCell(app.status || "RUNNING")}` +
        (app.route_prefix ? ` <span class="muted">${esc(app.route_prefix)}` +
         `</span>` : ``) + `</h3>` +
        `<table><tr><th>Deployment</th><th>Replicas</th><th>Target</th>` +
        `<th>Ongoing</th><th>Queue</th><th>Slots</th><th>KV hit</th>` +
        `<th>p50</th><th>p99</th><th>QPS</th></tr>` +
        Object.entries(deps).map(([d, info]) => {
          const s = (info && info.stats) || {};
          const slots = s.cb_slots
            ? `${esc(s.cb_active ?? 0)}/${esc(s.cb_slots)}` : "";
          const kv = ("kv_hit_rate" in s)
            ? `${Math.round(100 * s.kv_hit_rate)}% ` +
              `${((s.kv_bytes || 0) / 1e6).toFixed(1)}MB` : "";
          return `<tr><td>${esc(d)}</td>` +
            `<td>${esc((info && (info.num_replicas ?? info.replicas))
                       ?? "")}</td>` +
            `<td>${esc((info && info.target) ?? "")}</td>` +
            `<td>${esc(s.ongoing ?? 0)}</td>` +
            `<td>${esc(s.queue_depth ?? 0)}</td>` +
            `<td>${slots}</td>` +
            `<td>${kv}</td>` +
            `<td>${ms(s.p50_s)} ms</td><td>${ms(s.p99_s)} ms</td>` +
            `<td>${esc(s.qps ?? 0)}</td></tr>`;
        }).join("") + `</table>`;
    }).join("") +
    `<h3>Autoscaler decisions</h3>` +
    (decisions.length ? `<table><tr><th>When</th><th>Deployment</th>` +
      `<th>Target</th><th>Why</th></tr>` +
      decisions.slice().reverse().map(d => {
        const trig = d.trigger || {};
        const when = d.t ? new Date(d.t * 1000).toLocaleTimeString() : "";
        return `<tr><td>${esc(when)}</td>` +
          `<td>${esc(d.app)}/${esc(d.deployment)}</td>` +
          `<td>${esc(d.old_target)} &rarr; ${esc(d.new_target)} ` +
          `(${esc(d.direction || "")})</td>` +
          `<td>ongoing_avg=${esc(trig.ongoing_avg ?? 0)} ` +
          `queue=${esc(trig.queue_depth ?? 0)} ` +
          `p99=${ms(trig.p99_s)}ms qps=${esc(trig.qps ?? 0)}</td></tr>`;
      }).join("") + `</table>`
      : `<div class="empty">none recorded</div>`);
    return;
  }
  let rows = data[active] || [];
  if (active === "errors") rows = rows.slice().reverse();  // newest first
  const cols = COLS[active];
  if (!rows.length) {
    el.innerHTML = active === "steps"
      ? `<div class="empty">no step records yet — enable the step ` +
        `profiler (RT_STEP_PROFILER=1 or rt profile) and drain()</div>`
      : `<div class="empty">no ${esc(active)} yet</div>`;
    return;
  }
  el.innerHTML = (active === "steps" ? STEP_LEGEND
    : active === "tasks" && rows.some(r => r.phases) ? PHASE_LEGEND
    : "") + `<table><tr>` +
    cols.map(c => `<th>${esc(c[0])}</th>`).join("") + `</tr>` +
    rows.map(r => {
      const id = active === "actors" ? r.actor_id : null;
      const open = id && id === openActor;
      return `<tr${id ? ` class="clickable" data-actor="${esc(id)}"`
                      : ""}>` +
        cols.map(c => c[1](r)).join("") + `</tr>` +
        (open ? actorDetail(r) : "");
    }).join("") + `</table>`;
}

function renderTabs() {
  document.getElementById("tabs").innerHTML = TABS.map(t =>
    `<button data-id="${t.id}" class="${t.id === active ? "active" : ""}">` +
    `${esc(t.label)}</button>`).join("");
}

async function fetchJson(url) {
  const resp = await fetch(url);
  if (!resp.ok) throw new Error(`${url}: HTTP ${resp.status}`);
  return resp.json();
}
async function refresh(force) {
  if (paused && !force) return;
  try {
    const [nodes, actors, jobs, resources, tab] = await Promise.all([
      fetchJson("/api/nodes"), fetchJson("/api/actors"),
      fetchJson("/api/jobs"), fetchJson("/api/cluster_resources"),
      fetchJson(TABS.find(t => t.id === active).url),
    ]);
    data.nodes = nodes; data.actors = actors; data.jobs = jobs;
    data.resources = resources;
    data[active] = active === "serve" ? (tab || {}) : tab;
    renderTiles(); renderTable();
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
    document.getElementById("error").textContent = "";
  } catch (e) {
    document.getElementById("error").textContent = String(e);
  }
}

document.getElementById("tabs").addEventListener("click", e => {
  const id = e.target.dataset && e.target.dataset.id;
  if (!id) return;
  active = id; renderTabs();
  refresh(true);  // tab switch renders even while paused
});
document.getElementById("content").addEventListener("click", e => {
  const btn = e.target.closest(".stack-btn");
  if (btn) { fetchStacks(btn.dataset.node); return; }
  const row = e.target.closest("tr[data-actor]");
  if (!row) return;
  const id = row.dataset.actor;
  openActor = openActor === id ? null : id;
  renderTable();
});
document.getElementById("pause").addEventListener("click", e => {
  paused = !paused;
  e.target.textContent = paused ? "resume" : "pause";
});
document.getElementById("theme").addEventListener("click", () => {
  const root = document.documentElement;
  const cur = root.dataset.theme ||
    (matchMedia("(prefers-color-scheme: dark)").matches ? "dark" : "light");
  root.dataset.theme = cur === "dark" ? "light" : "dark";
});
fetchJson("/api/version").then(v => {
  document.getElementById("version").textContent =
    `${v.framework} ${v.version}`;
}).catch(() => {});
renderTabs();
refresh();
setInterval(refresh, 5000);
</script>
</body>
</html>
"""
