"""DashboardActor: aiohttp REST endpoints over the cluster's state.

Reference analogs: ``dashboard/head.py`` (aiohttp app + module routes),
``dashboard/state_aggregator.py`` + ``python/ray/util/state/api.py`` (the
State API), ``dashboard/modules/metrics`` (Prometheus). Routes:

  GET /api/version              build/version info
  GET /api/nodes                node table
  GET /api/actors               actor table
  GET /api/placement_groups     placement groups
  GET /api/tasks                recent task events
  GET /api/steps                step-profiler records (profile payloads)
  GET /api/objects              object directory
  GET /api/errors               failure plane (categorized FailureEvents)
  GET /api/memory               memory plane (store usage + owner ledgers)
  GET /api/logs                 worker log rings (?node=&worker=&limit=)
  GET /api/jobs                 submitted jobs
  GET /api/serve/applications   serve app states
  GET /api/sched                placement decisions + cross-node balance
  GET /api/engine               engine flight-recorder snapshots
  GET /api/rlhf                 RLHF pipeline flight-recorder snapshots
  GET /api/cluster_resources    total/available
  GET /metrics                  Prometheus text page
  GET /-/healthz                liveness
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional

import ray_tpu


@ray_tpu.remote
class DashboardActor:
    def __init__(self):
        self._runner = None
        self._port: Optional[int] = None

    async def start(self, host: str, port: int) -> int:
        from aiohttp import web

        if self._port is not None:
            return self._port  # idempotent: already serving
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/-/healthz", self._healthz)
        app.router.add_get("/api/version", self._version)
        app.router.add_get("/api/nodes", self._gcs_list("list_nodes"))
        app.router.add_get("/api/actors", self._gcs_list("list_actors"))
        app.router.add_get("/api/placement_groups",
                           self._gcs_list("list_placement_groups"))
        app.router.add_get("/api/tasks", self._gcs_list(
            "list_tasks", {"profile": "exclude"}))
        # step-profiler records (util/step_profiler.py): the per-step
        # device-time / MFU page reads the same store, profile rows only
        app.router.add_get("/api/steps", self._gcs_list(
            "list_tasks", {"profile": "only"}))
        app.router.add_get("/api/objects", self._gcs_list("list_objects"))
        # the failure plane: categorized FailureEvents (death-cause
        # taxonomy, core/failure.py) straight off the GCS store
        app.router.add_get("/api/errors",
                           self._gcs_list("list_failure_events"))
        app.router.add_get("/api/memory", self._memory)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/cluster_resources", self._cluster_resources)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/serve/applications", self._serve_apps)
        app.router.add_get("/api/serve", self._serve_detail)
        # the placement-receipt plane: decision records + the cross-node
        # balance snapshot (GCS placement_events store / sched_balance)
        app.router.add_get("/api/sched", self._sched)
        # the engine plane: flight-recorder snapshots (@engine/ KV —
        # tick phases, request lifecycles, SLO/goodput rollups)
        app.router.add_get("/api/engine", self._engine)
        # the RLHF plane: pipeline flight-recorder snapshots (@rlhf/ KV —
        # per-role bubble attribution, staleness, transfer receipts)
        app.router.add_get("/api/rlhf", self._rlhf)
        # the train plane: StepDriver flight-recorder snapshots (@train/
        # KV — launch phase attribution, launch-gap/data-starvation
        # accounting, the MFU-gap waterfall)
        app.router.add_get("/api/train", self._train)
        app.router.add_get("/api/stacks", self._stacks)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- handlers -------------------------------------------------------------
    async def _index(self, request):
        """The browser UI (reference: ``dashboard/client/`` React SPA —
        here a single static page over the same REST surface)."""
        from aiohttp import web

        from ray_tpu.dashboard.ui import INDEX_HTML

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="ok")

    async def _version(self, request):
        from aiohttp import web

        import ray_tpu as rt

        return web.json_response({"version": getattr(rt, "__version__", "dev"),
                                  "framework": "ray_tpu"})

    def _backend(self):
        return ray_tpu.global_worker()._require_backend()

    def _gcs_list(self, method: str, extra: Optional[Dict] = None):
        async def handler(request):
            from aiohttp import web

            loop = asyncio.get_running_loop()
            payload = {"limit": int(request.query.get("limit", 1000)),
                       **(extra or {})}
            rows = await loop.run_in_executor(
                None, lambda: self._backend().io.run(
                    self._backend()._gcs.call(method, payload)))
            return web.json_response(rows, dumps=_dumps)

        return handler

    async def _cluster_resources(self, request):
        from aiohttp import web

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            None, lambda: self._backend().io.run(
                self._backend()._gcs.call("cluster_resources", {})))
        return web.json_response(out, dumps=_dumps)

    async def _jobs(self, request):
        from aiohttp import web

        from ray_tpu.job import list_jobs

        loop = asyncio.get_running_loop()
        jobs = await loop.run_in_executor(None, list_jobs)
        return web.json_response(jobs, dumps=_dumps)

    async def _serve_apps(self, request):
        """Serve application states (reference: dashboard serve module)."""
        from aiohttp import web

        def fetch():
            from ray_tpu import serve

            try:
                return serve.status()
            except RuntimeError:  # serve not running
                return {}

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _serve_detail(self, request):
        """The Serve tab's payload: applications with per-deployment
        windowed stats (ongoing / queue depth / p50 / p99 / QPS) plus the
        autoscaler decision-log tail (serve/controller.py)."""
        from aiohttp import web

        def fetch():
            from ray_tpu import serve

            try:
                return serve.detailed_status()
            except RuntimeError:  # serve not running
                return {"applications": {}, "decisions": []}

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _sched(self, request):
        """The Scheduling tab's payload: the placement decision feed (kind,
        chosen node, reason, candidate feature vectors) joined with the
        cross-node balance snapshot (per-node queued+running load + the
        imbalance CoV behind rt_sched_node_imbalance)."""
        from aiohttp import web

        limit = int(request.query.get("limit", 200))
        kind = request.query.get("kind")

        def fetch():
            backend = self._backend()

            async def run():
                payload: Dict[str, Any] = {"limit": limit}
                if kind:
                    payload["kind"] = kind
                decisions, balance = await asyncio.gather(
                    backend._gcs.call("list_placement_events", payload),
                    backend._gcs.call("sched_balance", {}))
                return {"decisions": decisions, "balance": balance}

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _engine(self, request):
        """The Engine tab's payload: every live ContinuousEngine's
        flight-recorder snapshot (util/engine_recorder.py drain pushes
        them to the ``@engine/`` KV) — summary SLO/goodput rollup plus
        the tick-phase and request-lifecycle record tails."""
        from aiohttp import web

        def fetch():
            backend = self._backend()

            async def run():
                keys = (await backend._gcs.call(
                    "kv_keys", {"prefix": "@engine/"})).get("keys") or []
                replies = await asyncio.gather(
                    *(backend._gcs.call("kv_get", {"key": k})
                      for k in sorted(keys)[:50]))
                engines = []
                for reply in replies:
                    raw = reply.get("value")
                    if not raw:
                        continue
                    try:
                        engines.append(json.loads(raw))
                    except ValueError:
                        continue
                return {"engines": engines}

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _rlhf(self, request):
        """The RLHF tab's payload: every live RLHF pipeline's
        flight-recorder snapshot (util/pipeline_recorder.py drain pushes
        them to the ``@rlhf/`` KV) — bubble fraction, per-role idle
        attribution, staleness profile, and the last transfer receipt."""
        from aiohttp import web

        def fetch():
            backend = self._backend()

            async def run():
                keys = (await backend._gcs.call(
                    "kv_keys", {"prefix": "@rlhf/"})).get("keys") or []
                replies = await asyncio.gather(
                    *(backend._gcs.call("kv_get", {"key": k})
                      for k in sorted(keys)[:50]))
                pipelines = []
                for reply in replies:
                    raw = reply.get("value")
                    if not raw:
                        continue
                    try:
                        pipelines.append(json.loads(raw))
                    except ValueError:
                        continue
                return {"pipelines": pipelines}

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _train(self, request):
        """The Train tab's payload: every StepDriver's flight-recorder
        snapshot (util/train_recorder.py drain pushes them to the
        ``@train/`` KV) — per-launch phase walls, launch-gap accounting
        and the MFU-gap waterfall. Snapshots survive the driver, so a
        finished run stays inspectable here until the cluster dies."""
        from aiohttp import web

        def fetch():
            backend = self._backend()

            async def run():
                keys = (await backend._gcs.call(
                    "kv_keys", {"prefix": "@train/"})).get("keys") or []
                replies = await asyncio.gather(
                    *(backend._gcs.call("kv_get", {"key": k})
                      for k in sorted(keys)[:50]))
                drivers = []
                for reply in replies:
                    raw = reply.get("value")
                    if not raw:
                        continue
                    try:
                        drivers.append(json.loads(raw))
                    except ValueError:
                        continue
                return {"drivers": drivers}

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _stacks(self, request):
        """Cluster-wide live Python stacks (py-spy-equivalent, reference:
        ``dashboard/modules/reporter/profile_manager.py``): every node's
        raylet asks its workers to snapshot ``sys._current_frames()``.
        ``?node_id=`` limits to one node."""
        from aiohttp import web

        want = request.query.get("node_id")
        timeout = float(request.query.get("timeout", 3.0))

        def fetch():
            backend = self._backend()

            async def one(n):
                try:
                    client = await backend._pool.get(n["address"])
                    return await asyncio.wait_for(
                        client.call("dump_stacks", {"timeout": timeout}),
                        timeout=timeout + 2.0)
                except Exception as e:  # noqa: BLE001 — partial is fine
                    return {"node_id": n["node_id"],
                            "unreachable": f"{type(e).__name__}: {e}"}

            async def run():
                nodes = await backend._gcs.call("list_nodes", {})
                targets = [n for n in nodes
                           if (not want or n["node_id"] == want)
                           and n.get("alive", True)]
                # all nodes concurrently: worst case is ONE timeout, not
                # num_nodes stacked timeouts
                return list(await asyncio.gather(*(one(n)
                                                   for n in targets)))

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _memory(self, request):
        """The Memory tab's payload: per-node store reports joined with
        the ownership ledgers + recent OOM post-mortems (util/memory.py)."""
        from aiohttp import web

        from ray_tpu.util.memory import memory_snapshot, oom_reports

        limit = int(request.query.get("limit", 200))

        def fetch():
            snap = memory_snapshot(limit=limit)
            try:
                snap["oom_kills"] = oom_reports()
            except Exception:  # noqa: BLE001 — partial payload is fine
                snap["oom_kills"] = []
            return snap

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(None, fetch)
        return web.json_response(out, dumps=_dumps)

    async def _logs(self, request):
        """Worker log viewer: drains every raylet's bounded log ring
        (reference: the dashboard log endpoints over log_monitor state).
        ``?node=<id prefix>`` limits to one node, ``?worker=<id prefix>``
        to one worker, ``?limit=`` caps returned lines."""
        from aiohttp import web

        want_node = request.query.get("node")
        want_worker = request.query.get("worker")
        limit = int(request.query.get("limit", 500))

        def fetch():
            backend = self._backend()

            async def one(n):
                try:
                    client = await backend._pool.get(n["address"])
                    reply = await asyncio.wait_for(
                        client.call("poll_logs",
                                    {"after": 0, "timeout": 0.05}), 5.0)
                    return [{"node_id": n["node_id"], **e}
                            for e in reply.get("entries", ())]
                except Exception:  # noqa: BLE001 — partial view is fine
                    return []

            async def run():
                nodes = await backend._gcs.call("list_nodes", {})
                targets = [
                    n for n in nodes if n.get("alive", True)
                    and (not want_node
                         or n["node_id"].startswith(want_node))]
                chunks = await asyncio.gather(*(one(n) for n in targets))
                return [e for ch in chunks for e in ch]

            return backend.io.run(run())

        loop = asyncio.get_running_loop()
        entries = await loop.run_in_executor(None, fetch)
        if want_worker:
            entries = [e for e in entries
                       if str(e.get("worker_id", "")).startswith(
                           want_worker)]
        entries.sort(key=lambda e: (e.get("node_id", ""),
                                    e.get("seq", 0)))
        return web.json_response(entries[-limit:], dumps=_dumps)

    async def _metrics(self, request):
        """User metrics (pushed registries) + system series synthesized
        from cluster state at scrape time (reference: the metric_defs.cc
        built-ins exported by the per-node agent — here the dashboard IS
        the exporter, so the state API is the source of truth)."""
        from aiohttp import web

        from ray_tpu.util.metrics import metrics_text

        def fetch():
            text = metrics_text()
            try:
                text += system_metrics_text(self._backend())
            except Exception:  # noqa: BLE001 — user page still served
                pass
            return text

        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(None, fetch)
        return web.Response(text=text, content_type="text/plain")


def _dumps(obj: Any) -> str:
    return json.dumps(obj, default=str)


# System series synthesized per scrape; also the panel inventory for the
# generated Grafana dashboard (dashboard/grafana.py)
SYSTEM_METRICS = {
    "rt_nodes": ("gauge", "Cluster nodes by liveness"),
    "rt_actors": ("gauge", "Actors by state"),
    "rt_tasks": ("gauge", "Task events by state"),
    "rt_placement_groups": ("gauge", "Placement groups by state"),
    "rt_resource_total": ("gauge", "Cluster resource capacity"),
    "rt_resource_available": ("gauge", "Cluster resource availability"),
    "rt_objects_in_store": ("gauge", "Objects tracked in the directory"),
}


def system_metrics_text(backend) -> str:
    """Prometheus text for the framework's own state (nodes/actors/tasks/
    PGs/resources/objects), computed from the GCS at scrape time."""
    from collections import Counter as _Counter

    import asyncio as _asyncio

    async def gather():
        gcs = backend._gcs
        # concurrent: scrape latency is the MAX of the six calls, not
        # the sum (Prometheus scrapes every 10s)
        return await _asyncio.gather(
            gcs.call("list_nodes", {}),
            gcs.call("list_actors", {}),
            gcs.call("list_tasks", {"limit": 10_000}),
            gcs.call("list_placement_groups", {}),
            gcs.call("cluster_resources", {}),
            gcs.call("list_objects", {"limit": 100_000}))

    nodes, actors, tasks, pgs, res, objs = backend.io.run(gather())
    lines = []

    def emit(name, label_kv, value):
        labels = ",".join(f'{k}="{v}"' for k, v in label_kv)
        lines.append(f"{name}{{{labels}}} {value}"
                     if labels else f"{name} {value}")

    for name, (kind, desc) in SYSTEM_METRICS.items():
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {kind}")
        if name == "rt_nodes":
            alive = sum(1 for n in nodes if n.get("alive", True))
            emit(name, [("state", "alive")], alive)
            emit(name, [("state", "dead")], len(nodes) - alive)
        elif name == "rt_actors":
            for state, c in sorted(_Counter(
                    a.get("state", "?") for a in actors).items()):
                emit(name, [("state", state)], c)
        elif name == "rt_tasks":
            for state, c in sorted(_Counter(
                    t.get("state", "?") for t in tasks).items()):
                emit(name, [("state", state)], c)
        elif name == "rt_placement_groups":
            for state, c in sorted(_Counter(
                    p.get("state", "?") for p in pgs).items()):
                emit(name, [("state", state)], c)
        elif name == "rt_resource_total":
            for r, v in sorted((res.get("total") or {}).items()):
                emit(name, [("resource", r)], v)
        elif name == "rt_resource_available":
            for r, v in sorted((res.get("available") or {}).items()):
                emit(name, [("resource", r)], v)
        elif name == "rt_objects_in_store":
            emit(name, [], len(objs))
    return "\n".join(lines) + "\n"


_DASHBOARD_NAME = "RT_DASHBOARD"


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start (or find) the dashboard actor; returns the HTTP port."""
    try:
        actor = ray_tpu.get_actor(_DASHBOARD_NAME, namespace="_rt_dashboard")
    except ValueError:
        actor = DashboardActor.options(
            name=_DASHBOARD_NAME, namespace="_rt_dashboard",
            lifetime="detached", num_cpus=0, max_concurrency=32).remote()
    return ray_tpu.get(actor.start.remote(host, port))
