"""Grafana + Prometheus provisioning factory.

Reference analog: ``dashboard/modules/metrics/grafana_dashboard_factory.py``
(+ ``grafana_dashboard_provisioning_template.py``,
``grafana_datasource_template.py``, ``metrics_head.py`` writing the
prometheus scrape config). Redesign: the reference renders panel configs
defined in three hand-maintained dashboard modules; here ONE factory emits

  <out>/grafana/provisioning/dashboards/rt.yml      file provider
  <out>/grafana/provisioning/datasources/rt.yml     Prometheus datasource
  <out>/grafana/dashboards/rt_cluster.json          the cluster dashboard
  <out>/prometheus/prometheus.yml                   scrape config

pointed at this framework's single aggregated ``/metrics`` page (the
dashboard REST endpoint, ``dashboard/head.py``). Panels cover the system
series synthesized at scrape time (``head.SYSTEM_METRICS``) plus any user
metrics found in a live registry snapshot when a cluster is attached.

Start grafana with ``--config`` pointing provisioning at the generated
directory (or copy the files into /etc/grafana) and prometheus with the
generated ``prometheus.yml`` — turnkey, no clicking.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

_DATASOURCE_UID = "rt_prometheus"


def _panel(panel_id: int, title: str, expr: str, legend: str,
           unit: str = "short", x: int = 0, y: int = 0) -> Dict:
    """One timeseries panel (current Grafana schema, not the legacy
    'graph' type the reference still emits)."""
    return {
        "id": panel_id,
        "type": "timeseries",
        "title": title,
        "datasource": {"type": "prometheus", "uid": _DATASOURCE_UID},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {
            "defaults": {
                "unit": unit,
                "custom": {"fillOpacity": 10, "lineWidth": 1,
                           "stacking": {"mode": "none"}},
            },
            "overrides": [],
        },
        "options": {
            "legend": {"displayMode": "table", "placement": "bottom",
                       "calcs": ["lastNotNull"]},
            "tooltip": {"mode": "multi"},
        },
        "targets": [{
            "expr": expr,
            "legendFormat": legend,
            "refId": "A",
            "datasource": {"type": "prometheus",
                           "uid": _DATASOURCE_UID},
        }],
    }


def build_cluster_dashboard(
        user_metrics: Optional[List[Dict]] = None) -> Dict:
    """The default cluster dashboard: one panel per system series plus one
    per user metric (rate() for counters, raw for gauges, p50/p99 for
    histograms)."""
    panels: List[Dict] = []
    pid = 1

    def add(title, expr, legend, unit="short"):
        nonlocal pid
        n = len(panels)
        panels.append(_panel(pid, title, expr, legend, unit,
                             x=(n % 2) * 12, y=(n // 2) * 8))
        pid += 1

    add("Nodes", 'rt_nodes', "{{state}}")
    add("Actors by state", 'rt_actors', "{{state}}")
    add("Tasks by state", 'rt_tasks', "{{state}}")
    add("Placement groups", 'rt_placement_groups', "{{state}}")
    add("Resource utilization",
        'rt_resource_total - ignoring(state) rt_resource_available',
        "{{resource}} in use")
    add("Resource capacity", 'rt_resource_total', "{{resource}}")
    add("Objects in store", 'rt_objects_in_store', "objects")
    # failure plane (PR 5): the death-cause feed + recovery telemetry
    add("Failures by category", 'rate(rt_failures_total[5m])',
        "{{category}}")
    add("OOM kills", 'increase(rt_oom_kills_total[10m])', "{{node_id}}")
    add("Actor restarts", 'increase(rt_actor_restarts_total[10m])',
        "restarts")
    add("Task retries", 'increase(rt_task_retries_total[10m])', "retries")
    add("Raylet queue depth", 'rt_raylet_queue_depth', "{{node_id}}")

    for m in user_metrics or []:
        name, kind = m.get("name"), m.get("type", "gauge")
        if not name:
            continue
        if kind == "counter":
            add(f"{name} (rate)", f"rate({name}[5m])", "{{instance}}")
        elif kind == "histogram":
            add(f"{name} p50/p99",
                f"histogram_quantile(0.99, rate({name}_bucket[5m]))",
                "p99", unit="s")
            panels[-1]["targets"].append({
                "expr": f"histogram_quantile(0.50, "
                        f"rate({name}_bucket[5m]))",
                "legendFormat": "p50",
                "refId": "B",
                "datasource": {"type": "prometheus",
                               "uid": _DATASOURCE_UID},
            })
        else:
            add(name, name, "{{instance}}")

    return {
        "uid": "rt-cluster",
        "title": "ray_tpu cluster",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "schemaVersion": 39,
        "templating": {"list": []},
        "panels": panels,
    }


_DASHBOARD_PROVIDER_YML = """\
apiVersion: 1
providers:
  - name: ray_tpu
    folder: ray_tpu
    type: file
    disableDeletion: false
    allowUiUpdates: true
    options:
      path: {dashboards_dir}
"""

_DATASOURCE_YML = """\
apiVersion: 1
datasources:
  - name: rt-prometheus
    uid: %s
    type: prometheus
    access: proxy
    url: {prom_url}
    isDefault: true
""" % _DATASOURCE_UID

_PROMETHEUS_YML = """\
global:
  scrape_interval: 10s
  evaluation_interval: 10s
rule_files:
  - alert_rules.yml
scrape_configs:
  - job_name: ray_tpu
    metrics_path: /metrics
    static_configs:
      - targets: ['{metrics_target}']
"""


def export_grafana(out_dir: str,
                   prom_url: str = "http://127.0.0.1:9090",
                   metrics_target: str = "127.0.0.1:8265",
                   user_metrics: Optional[List[Dict]] = None
                   ) -> Dict[str, str]:
    """Write the full provisioning tree; returns {artifact: path}."""
    dash_dir = os.path.join(out_dir, "grafana", "dashboards")
    prov_dash = os.path.join(out_dir, "grafana", "provisioning",
                             "dashboards")
    prov_ds = os.path.join(out_dir, "grafana", "provisioning",
                           "datasources")
    prom_dir = os.path.join(out_dir, "prometheus")
    for d in (dash_dir, prov_dash, prov_ds, prom_dir):
        os.makedirs(d, exist_ok=True)

    paths = {}
    p = os.path.join(dash_dir, "rt_cluster.json")
    with open(p, "w") as f:
        json.dump(build_cluster_dashboard(user_metrics), f, indent=2)
    paths["dashboard"] = p

    p = os.path.join(prov_dash, "rt.yml")
    with open(p, "w") as f:
        f.write(_DASHBOARD_PROVIDER_YML.format(dashboards_dir=dash_dir))
    paths["dashboard_provider"] = p

    p = os.path.join(prov_ds, "rt.yml")
    with open(p, "w") as f:
        f.write(_DATASOURCE_YML.format(prom_url=prom_url))
    paths["datasource"] = p

    # alerting rules over the failure plane (scripts/alert_rules.yml is
    # the source of truth — linted by scripts/check_metrics.py); copied
    # next to prometheus.yml so the relative rule_files entry resolves.
    # Copied FIRST: prometheus.yml must only reference the file when the
    # copy landed (a dangling rule_files entry fails Prometheus startup).
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "scripts",
        "alert_rules.yml")
    p = os.path.join(prom_dir, "alert_rules.yml")
    have_rules = False
    try:
        with open(src) as f_in, open(p, "w") as f_out:
            f_out.write(f_in.read())
        paths["alert_rules"] = p
        have_rules = True
    except OSError:
        pass  # installed without the repo's scripts/ tree

    p = os.path.join(prom_dir, "prometheus.yml")
    yml = _PROMETHEUS_YML.format(metrics_target=metrics_target)
    if not have_rules:
        yml = yml.replace("rule_files:\n  - alert_rules.yml\n", "")
    with open(p, "w") as f:
        f.write(yml)
    paths["prometheus_config"] = p
    return paths


def snapshot_user_metrics() -> List[Dict]:
    """User metric descriptors from the attached cluster's pushed
    snapshots (name + type only — enough to choose a panel shape)."""
    import ray_tpu
    from ray_tpu.util import metrics as um

    backend = ray_tpu.global_worker()._require_backend()
    seen: Dict[str, Dict] = {}
    for key in backend.kv_keys(um._KV_PREFIX):
        raw = backend.kv_get(key)
        if not raw:
            continue
        try:
            for m in json.loads(raw)["metrics"]:
                seen.setdefault(m["name"], {"name": m["name"],
                                            "type": m.get("type", "gauge")})
        except (ValueError, KeyError):
            continue
    return sorted(seen.values(), key=lambda m: m["name"])
