"""Dashboard: REST state API over HTTP.

Reference analog: ``dashboard/`` — ``head.py`` (aiohttp ``DashboardHead``)
+ modules (actor/node/job/metrics/state). Redesign: no separate process
tree or React client; one actor serves the REST surface straight from GCS
RPCs and the metrics KV (the reference's ``state_aggregator.py`` role), and
the CLI (`rt dashboard`) starts it on demand.
"""

from ray_tpu.dashboard.head import start_dashboard  # noqa: F401
