"""Ownership/reference ledger: the per-process byte-side twin of tracing.

Reference analog: the core worker's ``ReferenceCounter``
(``core_worker/reference_count.h``) plus the aggregation behind
``ray memory`` / ``memory_summary()``: every process keeps a table of the
objects it owns (or holds refs to) — owner address, size, where the value
lives (memory store / plasma / local store), ref kinds (live local
``ObjectRef``s, uses as submitted-task args, gets served), creation time and
— behind ``RT_RECORD_REF_CREATION_SITES=1`` (Ray parity:
``RAY_record_ref_creation_sites``) — the Python call site that created the
ref.

Local-ref liveness is tracked with ``weakref.finalize`` on the ``ObjectRef``
objects themselves, so a ref dropped by user code decrements the count
without any explicit release call. The table is bounded; dead+freed entries
are evicted first.

Snapshots ride the same push plane as metrics: a daemon thread pushes this
process's ledger to the GCS KV under ``@memobj/<node>:<pid>`` so
``memory_summary()`` in the driver (and ``rt memory`` from outside) can join
owner/call-site info against the raylets' store reports. Everything here is
best-effort: the ledger must never fail or slow the data plane beyond a few
dict operations per ref.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

_KV_PREFIX = "@memobj/"
_PUSH_INTERVAL_S = 5.0
_MAX_ENTRIES = 65536
_SNAPSHOT_CAP = 2000  # largest-first entries per pushed snapshot


class _Entry:
    __slots__ = ("oid", "owner", "size", "where", "created_at", "call_site",
                 "local_refs", "task_arg_uses", "get_count", "last_get_at",
                 "freed")

    def __init__(self, oid: str):
        self.oid = oid
        self.owner: Optional[str] = None
        self.size: int = 0
        self.where: str = "unknown"   # memory | plasma | local | unknown
        self.created_at: float = time.time()
        self.call_site: str = ""
        self.local_refs: int = 0      # live ObjectRef objects in this process
        self.task_arg_uses: int = 0   # times passed as a remote-call argument
        self.get_count: int = 0       # times resolved through get()
        self.last_get_at: float = 0.0
        self.freed: bool = False

    def state(self) -> str:
        if self.freed:
            return "freed"
        return self.where

    def to_dict(self) -> Dict[str, Any]:
        return {"oid": self.oid, "owner": self.owner, "size": self.size,
                "state": self.state(), "created_at": self.created_at,
                "call_site": self.call_site, "local_refs": self.local_refs,
                "task_arg_uses": self.task_arg_uses,
                "get_count": self.get_count,
                "last_get_at": self.last_get_at}


class OwnershipLedger:
    def __init__(self):
        self._entries: Dict[str, _Entry] = {}  # rt: guarded-by(_lock)
        self._lock = threading.Lock()
        self._pusher: Optional[threading.Thread] = None
        self._record_sites: Optional[bool] = None  # lazy config read
        # deref backlog: ``_deref`` runs from weakref finalizers, which the
        # cyclic GC can fire on ANY thread at ANY allocation — including on
        # a thread that is inside one of the ``_lock`` regions below (e.g.
        # ``_entry`` allocating). A finalizer that takes ``_lock`` would
        # self-deadlock that thread and wedge every ObjectRef creation in
        # the process forever (seen live: a chaos kill-storm froze the
        # serve proxy's handle pool for 10+ minutes). Finalizers only
        # append here (deque.append is atomic under the GIL); the backlog
        # drains inside the next locked operation.
        self._pending_derefs: "collections.deque[str]" = collections.deque()

    # ---- config -------------------------------------------------------------
    def _sites_enabled(self) -> bool:
        if self._record_sites is None:
            from ray_tpu._private.config import get_config

            self._record_sites = get_config().record_ref_creation_sites
        return self._record_sites

    @staticmethod
    def _call_site() -> str:
        """First stack frame outside ray_tpu — where user code made the ref."""
        import traceback

        for frame in reversed(traceback.extract_stack(limit=24)):
            fname = frame.filename.replace(os.sep, "/")
            if "/ray_tpu/" not in fname and "object_ledger" not in fname:
                return f"{os.path.basename(frame.filename)}:" \
                       f"{frame.lineno} in {frame.name}"
        return ""

    # ---- recording ----------------------------------------------------------
    def _entry_locked(self, oid_hex: str) -> _Entry:
        e = self._entries.get(oid_hex)
        if e is None:
            if len(self._entries) >= _MAX_ENTRIES:
                self._evict_locked()
            e = self._entries[oid_hex] = _Entry(oid_hex)
        return e

    def _evict_locked(self) -> None:
        """Freed/dead entries first, oldest first; always frees some room."""
        items = sorted(self._entries.values(),
                       key=lambda e: (not (e.freed or e.local_refs == 0),
                                      e.created_at))
        for e in items[:max(1, _MAX_ENTRIES // 8)]:
            self._entries.pop(e.oid, None)

    def record_ref(self, ref) -> None:
        """Called from ObjectRef.__init__ (guarded by the config flag)."""
        try:
            oid_hex = ref.hex()
            site = self._call_site() if self._sites_enabled() else ""
            with self._lock:
                self._drain_derefs_locked()
                e = self._entry_locked(oid_hex)
                e.local_refs += 1
                if ref.owner_address() and not e.owner:
                    e.owner = ref.owner_address()
                if site and not e.call_site:
                    e.call_site = site
            weakref.finalize(ref, self._deref, oid_hex)
        except Exception:  # noqa: BLE001 — bookkeeping must never raise
            pass

    def _deref(self, oid_hex: str) -> None:
        # weakref-finalizer context: NEVER take ``_lock`` here (the GC can
        # fire this mid-allocation on a thread already holding it — see
        # ``_pending_derefs``); just enqueue, the next locked op drains
        self._pending_derefs.append(oid_hex)

    def _drain_derefs_locked(self) -> None:
        while True:
            try:
                oid_hex = self._pending_derefs.popleft()
            except IndexError:
                return
            e = self._entries.get(oid_hex)
            if e is not None and e.local_refs > 0:
                e.local_refs -= 1

    def record_put(self, oid_hex: str, size: int, where: str,
                   owner: Optional[str] = None) -> None:
        with self._lock:
            self._drain_derefs_locked()
            e = self._entry_locked(oid_hex)
            e.size = size
            e.where = where
            if owner:
                e.owner = owner

    def record_task_arg(self, oid_hex: str) -> None:
        with self._lock:
            self._drain_derefs_locked()
            e = self._entries.get(oid_hex)
            if e is not None:
                e.task_arg_uses += 1

    def record_get(self, oid_hex: str) -> None:
        with self._lock:
            self._drain_derefs_locked()
            e = self._entries.get(oid_hex)
            if e is not None:
                e.get_count += 1
                e.last_get_at = time.time()

    def record_freed(self, oid_hex: str) -> None:
        with self._lock:
            self._drain_derefs_locked()
            e = self._entries.get(oid_hex)
            if e is not None:
                e.freed = True

    # ---- access -------------------------------------------------------------
    def snapshot(self, cap: int = _SNAPSHOT_CAP) -> List[Dict[str, Any]]:
        with self._lock:
            self._drain_derefs_locked()
            entries = [e.to_dict() for e in self._entries.values()
                       if not e.freed]
        entries.sort(key=lambda d: -d["size"])
        return entries[:cap]

    def leak_suspects(self, age_s: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """Objects older than ``age_s`` whose only references are local refs
        held in this process (never consumed by a task, not freed)."""
        if age_s is None:
            from ray_tpu._private.config import get_config

            age_s = get_config().memory_leak_age_s
        now = time.time()
        with self._lock:
            self._drain_derefs_locked()
            out = []
            for e in self._entries.values():
                if e.freed or e.local_refs <= 0:
                    continue
                if now - e.created_at < age_s:
                    continue
                if e.task_arg_uses == 0 and (
                        e.last_get_at == 0.0
                        or now - e.last_get_at >= age_s):
                    d = e.to_dict()
                    d["age_s"] = now - e.created_at
                    out.append(d)
        out.sort(key=lambda d: -d["size"])
        return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    # ---- KV push (same pattern as util/metrics._Registry) -------------------
    def ensure_pusher(self) -> None:
        if self._pusher is not None and self._pusher.is_alive():
            return
        self._pusher = threading.Thread(target=self._push_loop, daemon=True,
                                        name="rt-ledger-push")
        self._pusher.start()

    def kv_key(self) -> str:
        return _KV_PREFIX + f"{os.uname().nodename}:{os.getpid()}"

    def flush_now(self) -> None:
        """Push this process's ledger snapshot immediately (tests; summary)."""
        import ray_tpu

        backend = ray_tpu.global_worker()._require_backend()
        backend.kv_put(self.kv_key(), json.dumps({
            "t": time.time(),
            "owner": getattr(backend, "address", "local"),
            "objects": self.snapshot()}).encode())

    def retract(self, backend) -> None:
        """Delete this process's KV snapshot (shutdown): a dead process's
        ledger must not keep reporting its objects as held."""
        try:
            backend.kv_del(self.kv_key())
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def _push_loop(self) -> None:
        import ray_tpu

        while True:
            time.sleep(_PUSH_INTERVAL_S)
            try:
                if not ray_tpu.is_initialized():
                    continue
                self.flush_now()
            except Exception:  # noqa: BLE001 — observability never takes
                pass  # the workload down


_ledger = OwnershipLedger()


def get_ledger() -> OwnershipLedger:
    return _ledger


_enabled: Optional[bool] = None


def enabled() -> bool:
    """One cached predicate on the hot ObjectRef path."""
    global _enabled
    if _enabled is None:
        try:
            from ray_tpu._private.config import get_config

            _enabled = get_config().object_ledger
        except Exception:  # noqa: BLE001 — config not importable yet
            return False
    return _enabled


def reset_enabled_for_tests() -> None:
    global _enabled
    _enabled = None
    _ledger._record_sites = None
