"""In-process runtime backend: threads instead of processes.

The local-mode analog of the reference's ``ray.init(local_mode=True)`` — but
with real concurrency: tasks run on their own threads, actors get dedicated
executors that preserve call ordering (a serial queue thread for
``max_concurrency=1``, a bounded pool for threaded actors, an asyncio loop
for async actors — mirroring the reference's ``ActorSchedulingQueue`` /
``BoundedExecutor`` / ``fiber.h`` trio in ``core_worker/transport/``).

Resource options are validated and *accounted* (cluster/available_resources
reflect them) but do not gate dispatch here — scheduling rigor lives in the
cluster backend's two-level scheduler, which is exercised separately. This
keeps local mode deadlock-free on small machines (a parent task blocked in
``get`` while its child waits for a CPU would otherwise hang).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import accelerator
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu.core import resources as res
from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.backend import RuntimeBackend
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.task_spec import resources_from_options, validate_options
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskCancelledError,
    TaskError,
)


def _estimate_size(value: Any) -> int:
    """Cheap in-memory footprint estimate (no serialization on the local
    hot path): array buffers dominate real workloads and expose nbytes."""
    import sys

    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(value, (bytes, bytearray, memoryview, str)):
        return len(value)
    try:
        return sys.getsizeof(value)
    except TypeError:
        return 0


class _ObjectStore:
    """Sealed-once object table with blocking reads."""

    def __init__(self):
        self._objects: Dict[ObjectID, Any] = {}
        # oid -> {"size": estimate, "t": seal time} for memory_summary()
        self._meta: Dict[ObjectID, Dict[str, float]] = {}
        self._cv = threading.Condition()

    def put(self, oid: ObjectID, value: Any) -> None:
        with self._cv:
            self._objects[oid] = value
            self._meta[oid] = {"size": _estimate_size(value),
                               "t": time.time()}
            self._cv.notify_all()

    def stats(self) -> Dict[str, Any]:
        """Store usage + per-object table for the memory plane."""
        with self._cv:
            objects = [{"oid": oid.hex(), "size": int(m["size"]),
                        "state": "in_memory",
                        "age_s": max(0.0, time.time() - m["t"])}
                       for oid, m in self._meta.items()]
        objects.sort(key=lambda d: -d["size"])
        return {"num_objects": len(objects),
                "used_bytes": sum(o["size"] for o in objects),
                "objects": objects}

    def contains(self, oid: ObjectID) -> bool:
        with self._cv:
            return oid in self._objects

    def get(self, oid: ObjectID, timeout: Optional[float]) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while oid not in self._objects:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"get() timed out waiting for {oid}")
                self._cv.wait(remaining)
            return self._objects[oid]

    def wait_any(self, oids: Sequence[ObjectID], num_ready: int,
                 timeout: Optional[float]) -> List[ObjectID]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                ready = [o for o in oids if o in self._objects]
                if len(ready) >= num_ready:
                    return ready[:num_ready] if num_ready < len(ready) else ready
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ready
                self._cv.wait(remaining)

    def free(self, oids: Sequence[ObjectID]) -> None:
        with self._cv:
            for o in oids:
                self._objects.pop(o, None)
                self._meta.pop(o, None)


class _ActorExecutor:
    """Per-actor execution context preserving submission order."""

    def __init__(self, instance: Any, max_concurrency: int):
        self.instance = instance
        self.dead = False
        self.death_reason = ""
        self._max_concurrency = max_concurrency
        self._is_async = False
        self._loop = None
        self._queue: "queue.Queue[Optional[Callable]]" = queue.Queue()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, is_async: bool) -> None:
        self._is_async = is_async
        if is_async:
            import asyncio

            self._loop = asyncio.new_event_loop()
            self._thread = threading.Thread(
                target=self._loop.run_forever, daemon=True, name="rt-async-actor")
            self._thread.start()
        elif self._max_concurrency > 1:
            self._pool = ThreadPoolExecutor(max_workers=self._max_concurrency,
                                            thread_name_prefix="rt-actor")
        else:
            self._thread = threading.Thread(target=self._serial_loop, daemon=True,
                                            name="rt-actor")
            self._thread.start()

    def _serial_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            item()

    def submit(self, thunk: Callable, coroutine_factory=None) -> None:
        if self.dead:
            raise ActorDiedError(reason=self.death_reason)
        if self._is_async and coroutine_factory is not None:
            import asyncio

            asyncio.run_coroutine_threadsafe(coroutine_factory(), self._loop)
        elif self._pool is not None:
            self._pool.submit(thunk)
        else:
            self._queue.put(thunk)

    def stop(self) -> None:
        self.dead = True
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        if self._thread is not None and not self._is_async:
            self._queue.put(None)


class _ActorRecord:
    def __init__(self, actor_id: ActorID, cls: type, name: Optional[str],
                 namespace: str, resources_req: ResourceSet, executor: _ActorExecutor):
        self.actor_id = actor_id
        self.cls = cls
        self.name = name
        self.namespace = namespace
        self.resources = resources_req
        self.executor = executor
        self.method_meta: Dict[str, int] = {}


class LocalBackend(RuntimeBackend):
    def __init__(self, job_id: JobID, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources_override: Optional[Dict[str, float]] = None,
                 namespace: Optional[str] = None):
        total = {
            res.CPU: num_cpus if num_cpus is not None else (os.cpu_count() or 1),
            res.TPU: num_tpus if num_tpus is not None
            else accelerator.autodetect_num_tpu_chips(),
            res.MEMORY: float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")),
        }
        total.update(resources_override or {})
        self._node = NodeResources({k: v for k, v in total.items() if v},
                                   labels=accelerator.tpu_node_labels())
        self._node_id_hex = os.urandom(16).hex()
        self.job_id = job_id
        self.namespace = namespace or "default"
        self._store = _ObjectStore()
        self._actors: Dict[ActorID, _ActorRecord] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._lock = threading.Lock()
        self._kv: Dict[str, bytes] = {}
        self._cancelled: set = set()
        self._shutdown = False

    # -- objects -------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        from ray_tpu.core.worker import global_worker
        from ray_tpu.core import object_ledger

        oid = global_worker().next_put_id()
        self._store.put(oid, value)
        if object_ledger.enabled():
            object_ledger.get_ledger().record_put(
                oid.hex(), _estimate_size(value), "local", owner="local")
        return ObjectRef(oid)

    def _resolve(self, value: Any) -> Any:
        """Replace top-level ObjectRef args with their values (like the
        reference's LocalDependencyResolver inlining)."""
        if isinstance(value, ObjectRef):
            out = self._store.get(value.id(), None)
            if isinstance(out, TaskError):
                raise out
            return out
        return value

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        from ray_tpu.core import object_ledger

        if object_ledger.enabled():
            ledger = object_ledger.get_ledger()
            for r in refs:
                ledger.record_get(r.hex())
        out = []
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            val = self._store.get(r.id(), remaining)
            if isinstance(val, (TaskError, ActorDiedError, TaskCancelledError)):
                raise val
            out.append(val)
        return out

    def wait(self, refs, num_returns, timeout):
        ready_ids = set(self._store.wait_any([r.id() for r in refs], num_returns, timeout))
        ready = [r for r in refs if r.id() in ready_ids]
        not_ready = [r for r in refs if r.id() not in ready_ids]
        return ready, not_ready

    def free_objects(self, refs: Sequence[ObjectRef]) -> None:
        from ray_tpu.core import object_ledger

        self._store.free([r.id() for r in refs])
        if object_ledger.enabled():
            ledger = object_ledger.get_ledger()
            for r in refs:
                ledger.record_freed(r.hex())

    def memory_report(self) -> Dict[str, Any]:
        """The local-mode analog of the raylet's memory_report RPC: one
        synthetic node whose store is the in-process object table."""
        stats = self._store.stats()
        return {"node_id": self._node_id_hex, "address": "local",
                "store": {"used_bytes": stats["used_bytes"],
                          "capacity_bytes": 0,
                          "in_mem_bytes": stats["used_bytes"],
                          "spilled_bytes": 0, "spilled_count": 0,
                          "pinned_count": 0,
                          "num_objects": stats["num_objects"],
                          "spills": 0, "restores": 0,
                          "spill_seconds": 0.0, "restore_seconds": 0.0,
                          "pin_purges": 0, "oom_kills": 0},
                "objects": stats["objects"], "workers": []}

    # -- tasks ---------------------------------------------------------------
    def submit_task(self, fn, options, args, kwargs):
        validate_options(options, for_actor=False)
        req = resources_from_options(options, default_num_cpus=1)
        num_returns = options.get("num_returns", 1)
        task_id = TaskID.for_task(self.job_id)
        if num_returns == "streaming":
            return self._submit_streaming(
                fn, args, kwargs, task_id,
                options.get("_stream_max_buffer", 16))
        refs = [ObjectRef(ObjectID.for_return(task_id, i)) for i in range(num_returns)]

        def run():
            if task_id in self._cancelled:
                self._seal_error(refs, TaskCancelledError(task_id))
                return
            self._execute(fn, args, kwargs, refs, task_id, fn.__name__)

        t = threading.Thread(target=run, daemon=True, name=f"rt-task-{fn.__name__}")
        t.start()
        self._register_resources(req)
        return refs[0] if num_returns == 1 else refs

    def _submit_streaming(self, fn, args, kwargs, task_id, max_buffer: int):
        """Thread-driven ``num_returns="streaming"``: items land in the local
        store as produced; a bounded queue is the backpressure."""
        import queue as _q

        out: _q.Queue = _q.Queue(maxsize=max(1, max_buffer))
        store = self._store
        name = getattr(fn, "__name__", "generator")
        closed = threading.Event()

        def _put(item) -> bool:
            """Bounded put that aborts when the consumer abandoned us."""
            while not closed.is_set():
                try:
                    out.put(item, timeout=0.2)
                    return True
                except _q.Full:
                    continue
            return False

        def run():
            i = 0
            try:
                rargs = [self._resolve(a) for a in args]
                rkwargs = {k: self._resolve(v) for k, v in kwargs.items()}
                for v in fn(*rargs, **rkwargs):
                    ref = ObjectRef(ObjectID.for_return(task_id, i))
                    store.put(ref.id(), v)
                    if not _put(ref):
                        return  # abandoned: stop producing
                    i += 1
            except BaseException as e:  # noqa: BLE001
                err = e if isinstance(e, TaskError) else TaskError(name, e)
                ref = ObjectRef(ObjectID.for_return(task_id, i))
                store.put(ref.id(), err)
                _put(ref)
            _put(None)

        threading.Thread(target=run, daemon=True,
                         name=f"rt-stream-{name}").start()

        class _LocalRefGenerator:
            def __iter__(self):
                return self

            def __next__(self):
                if closed.is_set():
                    raise StopIteration
                ref = out.get()
                if ref is None:
                    raise StopIteration
                return ref

            def close(self):
                closed.set()

            def __del__(self):
                closed.set()

        return _LocalRefGenerator()

    def _register_resources(self, req: ResourceSet) -> None:
        # Accounting only (see module docstring); release is immediate.
        pass

    def _seal_error(self, refs: List[ObjectRef], err: Exception) -> None:
        for r in refs:
            self._store.put(r.id(), err)

    def _execute(self, fn, args, kwargs, refs, task_id, name):
        from ray_tpu.core.worker import global_worker

        worker = global_worker()
        token = worker.enter_task_context(task_id)
        try:
            rargs = [self._resolve(a) for a in args]
            rkwargs = {k: self._resolve(v) for k, v in kwargs.items()}
            result = fn(*rargs, **rkwargs)
            self._seal_returns(refs, result)
        except TaskError as e:
            self._seal_error(refs, e)
        except BaseException as e:  # noqa: BLE001 — must seal something
            self._seal_error(refs, TaskError(name, e))
        finally:
            worker.exit_task_context(token)

    def _seal_returns(self, refs: List[ObjectRef], result: Any) -> None:
        if len(refs) == 1:
            self._store.put(refs[0].id(), result)
        else:
            vals = list(result) if result is not None else [None] * len(refs)
            if len(vals) != len(refs):
                err = TaskError("<returns>", ValueError(
                    f"expected {len(refs)} return values, got {len(vals)}"))
                self._seal_error(refs, err)
                return
            for r, v in zip(refs, vals):
                self._store.put(r.id(), v)

    # -- actors --------------------------------------------------------------
    def create_actor(self, cls, options, args, kwargs, method_meta):
        validate_options(options, for_actor=True)
        name = options.get("name")
        ns = options.get("namespace") or self.namespace
        with self._lock:
            if name is not None and (ns, name) in self._named_actors:
                if options.get("get_if_exists"):
                    aid = self._named_actors[(ns, name)]
                    rec = self._actors[aid]
                    return ActorHandle(aid, cls.__name__, rec.method_meta)
                raise ValueError(f"actor name {name!r} already taken in namespace {ns!r}")
        req = resources_from_options(options, default_num_cpus=0)
        actor_id = ActorID.of(self.job_id)
        max_conc = options.get("max_concurrency") or 1
        executor = _ActorExecutor(None, max_conc)
        rec = _ActorRecord(actor_id, cls, name, ns, req, executor)
        rec.method_meta = method_meta
        with self._lock:
            self._actors[actor_id] = rec
            if name is not None:
                self._named_actors[(ns, name)] = actor_id
        self._node.allocate(req) if self._node.can_fit(req) else None

        import inspect

        is_async = any(
            inspect.iscoroutinefunction(m) for _, m in
            inspect.getmembers(cls, predicate=inspect.isfunction))
        init_done = threading.Event()
        init_error: List[BaseException] = []

        def do_init():
            try:
                rargs = [self._resolve(a) for a in args]
                rkwargs = {k: self._resolve(v) for k, v in kwargs.items()}
                executor.instance = cls(*rargs, **rkwargs)
            except BaseException as e:  # noqa: BLE001
                init_error.append(e)
                executor.dead = True
                executor.death_reason = f"__init__ failed: {e!r}"
            finally:
                init_done.set()

        executor.start(is_async)
        if is_async:
            import asyncio

            async def _ainit():
                do_init()

            asyncio.run_coroutine_threadsafe(_ainit(), executor._loop)
        else:
            executor.submit(do_init)
        return ActorHandle(actor_id, cls.__name__, method_meta, original_handle=True)

    def submit_actor_task(self, actor_id, method_name, args, kwargs, num_returns):
        with self._lock:
            rec = self._actors.get(actor_id)
        if rec is None:
            raise ActorDiedError(actor_id, "unknown actor")
        task_id = TaskID.for_actor_task(actor_id)
        refs = [ObjectRef(ObjectID.for_return(task_id, i)) for i in range(num_returns)]
        executor = rec.executor
        if executor.dead:
            self._seal_error(refs, ActorDiedError(actor_id, executor.death_reason))
            return refs[0] if num_returns == 1 else refs

        import inspect

        raw_method = getattr(rec.cls, method_name, None)
        is_coro = inspect.iscoroutinefunction(raw_method)

        def thunk():
            if executor.dead or executor.instance is None and executor.dead:
                self._seal_error(refs, ActorDiedError(actor_id, executor.death_reason))
                return
            bound = getattr(executor.instance, method_name)
            self._execute(bound, args, kwargs, refs, task_id,
                          f"{rec.cls.__name__}.{method_name}")

        async def coro():
            from ray_tpu.core.worker import global_worker

            worker = global_worker()
            token = worker.enter_task_context(task_id)
            try:
                bound = getattr(executor.instance, method_name)
                rargs = [self._resolve(a) for a in args]
                rkwargs = {k: self._resolve(v) for k, v in kwargs.items()}
                result = await bound(*rargs, **rkwargs)
                self._seal_returns(refs, result)
            # rt: lint-allow(except-discipline) error transport: sealing
            # the error IS the unwind path — getters would hang forever
            # on an unsealed ref (see _seal_error's "must seal something")
            except BaseException as e:  # noqa: BLE001
                self._seal_error(refs, TaskError(method_name, e))
            finally:
                worker.exit_task_context(token)

        try:
            executor.submit(thunk, coroutine_factory=coro if is_coro else None)
        except ActorDiedError as e:
            self._seal_error(refs, e)
        return refs[0] if num_returns == 1 else refs

    def kill_actor(self, actor_id, no_restart=True):
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            rec.executor.death_reason = "killed via kill()"
            rec.executor.stop()
            if rec.name is not None:
                self._named_actors.pop((rec.namespace, rec.name), None)
        self._node.release(rec.resources)

    def get_actor_handle(self, name, namespace):
        ns = namespace or self.namespace
        with self._lock:
            aid = self._named_actors.get((ns, name))
            if aid is None:
                raise ValueError(f"no actor named {name!r} in namespace {ns!r}")
            rec = self._actors[aid]
            return ActorHandle(aid, rec.cls.__name__, rec.method_meta)

    # -- misc ----------------------------------------------------------------
    def cancel(self, ref, force=False):
        self._cancelled.add(ref.id().task_id())

    def cluster_resources(self):
        return self._node.total.to_dict()

    def available_resources(self):
        return self._node.available.to_dict()

    def nodes(self):
        return [{
            "node_id": self._node_id_hex,
            "alive": True,
            "resources": self._node.total.to_dict(),
            "labels": dict(self._node.labels),
            "address": "local",
        }]

    def kv_put(self, key, value):
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key):
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key):
        with self._lock:
            self._kv.pop(key, None)

    def kv_keys(self, prefix):
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        with self._lock:
            actors = list(self._actors.values())
        for rec in actors:
            rec.executor.stop()
        self._actors.clear()
        self._named_actors.clear()
