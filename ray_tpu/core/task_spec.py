"""Task and actor specifications + options validation.

Equivalent of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h``) and the Python options layer
(``python/ray/_private/ray_option_utils.py``): a language-neutral record of
what to run, with what resources, under which scheduling strategy. Functions
are exported once to the GCS function table and referenced by id
(``_private/function_manager.py``), so specs stay small on the hot path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, TaskID
from ray_tpu.core.resources import CPU, ResourceSet, TPU


@dataclasses.dataclass
class SchedulingStrategy:
    """Base: DEFAULT hybrid policy."""

    kind: str = "DEFAULT"


@dataclasses.dataclass
class SpreadStrategy(SchedulingStrategy):
    kind: str = "SPREAD"


@dataclasses.dataclass
class NodeAffinityStrategy(SchedulingStrategy):
    kind: str = "NODE_AFFINITY"
    node_id_hex: str = ""
    soft: bool = False


@dataclasses.dataclass
class NodeLabelStrategy(SchedulingStrategy):
    """Schedule onto nodes matching label constraints (reference:
    ``NodeLabelSchedulingPolicy`` + ``NodeLabelSchedulingStrategy``).

    ``hard`` must match; ``soft`` prefers matching nodes but falls back.
    Each value is one match expression:

    - ``"v"``        — label equals v (In)
    - ``["a", "b"]`` — label in {a, b} (In)
    - ``"!v"``       — label not equal v (NotIn)
    - ``"*"``        — label exists (Exists)
    - ``"!*"``       — label absent (DoesNotExist)

    e.g. ``NodeLabelStrategy(hard={"tpu-slice-name": "slice-0"},
    soft={"accelerator-type": ["TPU-V5P", "TPU-V5E"]})``.
    """

    kind: str = "NODE_LABEL"
    hard: Dict[str, Any] = dataclasses.field(default_factory=dict)
    soft: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PlacementGroupStrategy(SchedulingStrategy):
    kind: str = "PLACEMENT_GROUP"
    placement_group_id_hex: str = ""
    bundle_index: int = -1
    capture_child_tasks: bool = False


_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "memory", "resources", "name",
    "lifetime", "max_retries", "max_restarts", "max_task_retries",
    "num_returns", "scheduling_strategy", "placement_group",
    "placement_group_bundle_index", "max_concurrency", "runtime_env",
    "namespace", "get_if_exists", "max_pending_calls", "retry_exceptions",
    "concurrency_groups", "label_selector", "_stream_max_buffer",
    "deadline_s", "on_overload",
}


def validate_options(opts: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    for k in opts:
        if k not in _VALID_OPTIONS:
            raise ValueError(f"invalid option {k!r}; valid: {sorted(_VALID_OPTIONS)}")
    for k in ("num_cpus", "num_tpus", "num_gpus", "memory"):
        v = opts.get(k)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            raise ValueError(f"{k} must be a non-negative number, got {v!r}")
    n_tpus = opts.get("num_tpus")
    if n_tpus is not None and n_tpus > 1 and int(n_tpus) != n_tpus:
        raise ValueError("num_tpus must be a whole number when > 1 (chips are not divisible)")
    if not for_actor:
        for k in ("max_restarts", "max_task_retries", "max_concurrency"):
            if opts.get(k) is not None:
                raise ValueError(f"option {k!r} is only valid for actors")
    else:
        for k in ("deadline_s", "on_overload"):
            if opts.get(k) is not None:
                raise ValueError(f"option {k!r} is only valid for tasks")
    d = opts.get("deadline_s")
    if d is not None and (not isinstance(d, (int, float)) or d <= 0):
        raise ValueError(f"deadline_s must be a positive number, got {d!r}")
    oo = opts.get("on_overload")
    if oo not in (None, "block", "fail"):
        raise ValueError(f"on_overload must be 'block' or 'fail', got {oo!r}")
    return opts


def resources_from_options(opts: Dict[str, Any], default_num_cpus: float) -> ResourceSet:
    req: Dict[str, float] = dict(opts.get("resources") or {})
    for name in (CPU, TPU, "GPU", "memory"):
        if name in req:
            raise ValueError(f"use num_cpus/num_tpus/... instead of resources[{name!r}]")
    num_cpus = opts.get("num_cpus")
    req[CPU] = default_num_cpus if num_cpus is None else num_cpus
    if opts.get("num_tpus"):
        req[TPU] = opts["num_tpus"]
    if opts.get("num_gpus"):
        req["GPU"] = opts["num_gpus"]
    if opts.get("memory"):
        req["memory"] = opts["memory"]
    return ResourceSet(req)


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function_id: str                       # key into the GCS function table
    function_name: str                     # human-readable, for errors/state
    args: Tuple = ()                       # already-serialized or plain values
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_returns: int = 1
    resources: ResourceSet = dataclasses.field(default_factory=ResourceSet)
    scheduling_strategy: SchedulingStrategy = dataclasses.field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    runtime_env: Optional[dict] = None
    # Actor-task fields
    actor_id: Optional[ActorID] = None
    sequence_number: int = -1              # per-caller ordering for actor tasks
    is_actor_creation: bool = False

    @property
    def scheduling_key(self) -> Tuple:
        """Tasks with the same key can reuse a worker lease (reference:
        ``direct_task_transport.h`` scheduling_key)."""
        return (self.function_id, tuple(sorted(self.resources.to_dict().items())),
                self.scheduling_strategy.kind)


@dataclasses.dataclass
class ActorCreationSpec:
    actor_id: ActorID
    job_id: JobID
    class_id: str
    class_name: str
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    resources: ResourceSet = dataclasses.field(default_factory=ResourceSet)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None         # None | "detached"
    scheduling_strategy: SchedulingStrategy = dataclasses.field(default_factory=SchedulingStrategy)
    runtime_env: Optional[dict] = None
