"""Runtime backend interface.

The worker-facing contract implemented by both the in-process local backend
(``local_backend.py``) and the multiprocess cluster backend
(``cluster/driver_backend.py``). Equivalent in role to the reference's
``CoreWorker`` C-ABI surface (``src/ray/core_worker/core_worker.h:285``):
SubmitTask / CreateActor / SubmitActorTask / Put / Get / Wait plus lifecycle.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef


class RuntimeBackend(abc.ABC):
    @abc.abstractmethod
    def put(self, value: Any) -> ObjectRef: ...

    @abc.abstractmethod
    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]: ...

    @abc.abstractmethod
    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]: ...

    @abc.abstractmethod
    def submit_task(self, fn: Callable, options: Dict[str, Any],
                    args: Tuple, kwargs: Dict) -> Any: ...

    @abc.abstractmethod
    def create_actor(self, cls: type, options: Dict[str, Any], args: Tuple,
                     kwargs: Dict, method_meta: Dict[str, int]) -> Any: ...

    @abc.abstractmethod
    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: Tuple, kwargs: Dict, num_returns: int) -> Any: ...

    @abc.abstractmethod
    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None: ...

    @abc.abstractmethod
    def cancel(self, ref: ObjectRef, force: bool) -> None: ...

    @abc.abstractmethod
    def get_actor_handle(self, name: str, namespace: Optional[str]) -> Any: ...

    @abc.abstractmethod
    def cluster_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def available_resources(self) -> Dict[str, float]: ...

    @abc.abstractmethod
    def nodes(self) -> List[Dict]: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    # Optional hooks ---------------------------------------------------------
    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def kv_del(self, key: str) -> None:
        raise NotImplementedError

    def kv_keys(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def free_objects(self, refs: Sequence[ObjectRef]) -> None:
        """Release storage for objects (reference: ray._private.internal_api.free)."""
