"""The per-process worker singleton and the public entry points.

Equivalent of the reference's ``python/ray/_private/worker.py`` (global
``Worker`` singleton; ``init :1133``, ``shutdown :1698``, ``get_objects
:737``, ``put_object :659``): holds the runtime backend, the serialization
context, and the per-thread task context (current task id, put counter) that
object IDs for ``put`` are derived from.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.serialization import SerializationContext
from ray_tpu.core.backend import RuntimeBackend
from ray_tpu.core.object_ref import ObjectRef


class _TaskContext(threading.local):
    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.put_counter: int = 0
        self.actor_id: Optional[ActorID] = None


class Worker:
    def __init__(self):
        self.backend: Optional[RuntimeBackend] = None
        self.serialization_context = SerializationContext()
        self.job_id: Optional[JobID] = None
        self.mode: Optional[str] = None  # "local" | "driver" | "worker"
        self._ctx = _TaskContext()
        self._driver_task_id: Optional[TaskID] = None
        self._put_lock = threading.Lock()
        self._executor = None  # lazy pool for as_future

    # -- lifecycle -----------------------------------------------------------
    @property
    def connected(self) -> bool:
        return self.backend is not None

    def connect(self, backend: RuntimeBackend, job_id: JobID, mode: str) -> None:
        self.backend = backend
        self.job_id = job_id
        self.mode = mode
        self._driver_task_id = TaskID.for_task(job_id)

    def disconnect(self) -> None:
        if self.backend is not None:
            self.backend.shutdown()
        self.backend = None
        self.mode = None
        try:
            # the ownership ledger is session state: entries must not leak
            # into the next init() in this process (tests re-init a lot)
            from ray_tpu.core import object_ledger

            object_ledger.get_ledger().clear()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def _require_backend(self) -> RuntimeBackend:
        if self.backend is None:
            raise RuntimeError(
                "ray_tpu has not been initialized; call ray_tpu.init() first")
        return self.backend

    # -- task context --------------------------------------------------------
    def current_task_id(self) -> TaskID:
        return self._ctx.task_id or self._driver_task_id

    def current_actor_id(self) -> Optional[ActorID]:
        return self._ctx.actor_id

    def enter_task_context(self, task_id: TaskID, actor_id: Optional[ActorID] = None):
        token = (self._ctx.task_id, self._ctx.put_counter, self._ctx.actor_id)
        self._ctx.task_id = task_id
        self._ctx.put_counter = 0
        self._ctx.actor_id = actor_id
        return token

    def exit_task_context(self, token) -> None:
        self._ctx.task_id, self._ctx.put_counter, self._ctx.actor_id = token

    def next_put_id(self) -> ObjectID:
        with self._put_lock:
            self._ctx.put_counter += 1
            return ObjectID.for_put(self.current_task_id(), self._ctx.put_counter)

    # -- data plane ----------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed (reference parity)")
        return self._require_backend().put(value)

    def get(self, refs: Union[ObjectRef, Sequence[ObjectRef]],
            timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r).__name__}")
        values = self._require_backend().get(ref_list, timeout)
        return values[0] if single else values

    async def get_async(self, ref: ObjectRef) -> Any:
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._future_pool(), lambda: self.get(ref))

    def as_future(self, ref: ObjectRef):
        return self._future_pool().submit(lambda: self.get(ref))

    def _future_pool(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(max_workers=8,
                                                thread_name_prefix="rt-get")
        return self._executor

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        ref_list = list(refs)
        if len(set(ref_list)) != len(ref_list):
            raise ValueError("wait() got duplicate ObjectRefs")
        if num_returns <= 0 or num_returns > len(ref_list):
            raise ValueError(f"num_returns must be in [1, {len(ref_list)}]")
        return self._require_backend().wait(ref_list, num_returns, timeout)

    # -- control plane -------------------------------------------------------
    def submit_task(self, fn, options: Dict, args: Tuple, kwargs: Dict):
        from ray_tpu.util import tracing

        # phase tracing: stamp the submit entry so the span's ``submit``
        # phase covers arg serialization (no-op predicate when untraced)
        tracing.mark_submit_entry()
        return self._require_backend().submit_task(fn, options, args, kwargs)

    def create_actor(self, cls, options: Dict, args: Tuple, kwargs: Dict,
                     method_meta: Dict[str, int]):
        return self._require_backend().create_actor(cls, options, args, kwargs,
                                                    method_meta)

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args, kwargs,
                          num_returns: int = 1):
        from ray_tpu.util import tracing

        tracing.mark_submit_entry()
        return self._require_backend().submit_actor_task(
            actor_id, method_name, args, kwargs, num_returns)


_global_worker = Worker()


def global_worker() -> Worker:
    return _global_worker


# ---------------------------------------------------------------------------
# Public module-level API (re-exported from ray_tpu/__init__.py)
# ---------------------------------------------------------------------------

def init(address: Optional[str] = None, *,
         local_mode: bool = False,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict] = None) -> "RuntimeInfo":
    """Start (or connect to) a runtime.

    - ``address=None``: start a fresh single-node cluster runtime in this
      process tree (processes for head/raylet/workers), like the reference's
      default ``ray.init()``.
    - ``address="local"`` or ``local_mode=True``: in-process threaded backend.
    - ``address="<host>:<port>"``: connect to an existing head node.
    """
    w = _global_worker
    if w.connected:
        if ignore_reinit_error:
            return RuntimeInfo(w)
        raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
    if _system_config:
        import ray_tpu._private.config as cfgmod

        cfg = cfgmod.get_config()
        for k, v in _system_config.items():
            setattr(cfg, k, v)
    job_id = JobID.from_random()
    if local_mode or address == "local":
        from ray_tpu.core.local_backend import LocalBackend

        backend = LocalBackend(job_id, num_cpus=num_cpus, num_tpus=num_tpus,
                               resources_override=resources, namespace=namespace)
        w.connect(backend, job_id, "local")
        return RuntimeInfo(w)
    from ray_tpu.cluster.driver_backend import start_or_connect

    backend = start_or_connect(address, job_id, num_cpus=num_cpus,
                               num_tpus=num_tpus, resources=resources,
                               namespace=namespace)
    w.connect(backend, job_id, "driver")
    return RuntimeInfo(w)


def shutdown() -> None:
    _global_worker.disconnect()


def is_initialized() -> bool:
    return _global_worker.connected


class RuntimeInfo:
    """Returned by init(); context-manager support for scoped sessions."""

    def __init__(self, worker: Worker):
        self._worker = worker

    @property
    def address_info(self) -> Dict:
        nodes = self._worker.backend.nodes()
        return nodes[0] if nodes else {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        shutdown()
