"""Death-cause taxonomy: every failure in the system gets a typed cause.

Reference analog: ``src/ray/protobuf/common.proto`` — ``RayErrorInfo`` /
``ActorDeathCause`` / ``ErrorType``. The runtime used to speak in plain
strings (``death_reason = ""``); this module gives each kill/except site a
structured :class:`FailureCause` that

  - carries a **category** from a closed enum (mapped onto the public
    ``exceptions.py`` classes),
  - still renders as a human string (``str(cause)``) so existing
    death-reason plumbing keeps printing sensibly,
  - serializes to a plain dict for the RPC wire and the GCS
    ``failure_events`` store (``rt errors`` / ``/api/errors`` / the
    timeline's ``errors`` lane read it back).

Counting happens in exactly ONE place: the GCS increments
``rt_failures_total{category=}`` per stored report in
``GcsServer._record_failure`` (its registry is shipped by the co-resident
pusher — the driver's, or the head raylet's for standalone daemons).
Emitters must NOT call :func:`observe_failure` themselves — a local count
plus the GCS count would double every failure and skew the
``scripts/alert_rules.yml`` thresholds.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, Optional, Type

# ---- the category enum ------------------------------------------------------
# Categories map 1:1 onto exceptions.py classes (see EXCEPTION_FOR); keep
# the values kebab-free snake_case — they are Prometheus label values.
TASK_ERROR = "task_error"                        # TaskError
WORKER_CRASH = "worker_crash"                    # WorkerCrashedError
OOM_KILL = "oom_kill"                            # OutOfMemoryError
NODE_DEATH = "node_death"                        # NodeDiedError
ACTOR_RESTART_EXHAUSTED = "actor_restart_exhausted"  # ActorDiedError
SCHEDULING_TIMEOUT = "scheduling_timeout"        # ActorUnschedulableError
PG_REMOVED = "pg_removed"                        # ActorDiedError (bundle gone)
RUNTIME_ENV_SETUP = "runtime_env_setup"          # RuntimeEnvSetupError
OBJECT_LOST = "object_lost"                      # ObjectLostError
OWNER_DIED = "owner_died"                        # OwnerDiedError
GET_TIMEOUT = "get_timeout"                      # GetTimeoutError
CANCELLED = "cancelled"                          # TaskCancelledError / kill()
UNKNOWN = "unknown"                              # free-text legacy reasons

CATEGORIES = (
    TASK_ERROR, WORKER_CRASH, OOM_KILL, NODE_DEATH,
    ACTOR_RESTART_EXHAUSTED, SCHEDULING_TIMEOUT, PG_REMOVED,
    RUNTIME_ENV_SETUP, OBJECT_LOST, OWNER_DIED, GET_TIMEOUT, CANCELLED,
    UNKNOWN,
)


def _exception_map() -> Dict[str, Type[BaseException]]:
    # lazy: exceptions.py is import-light, but keep the module cycle-free
    from ray_tpu import exceptions as E

    return {
        TASK_ERROR: E.TaskError,
        WORKER_CRASH: E.WorkerCrashedError,
        OOM_KILL: E.OutOfMemoryError,
        NODE_DEATH: E.NodeDiedError,
        ACTOR_RESTART_EXHAUSTED: E.ActorDiedError,
        SCHEDULING_TIMEOUT: E.ActorUnschedulableError,
        PG_REMOVED: E.ActorDiedError,
        RUNTIME_ENV_SETUP: E.RuntimeEnvSetupError,
        OBJECT_LOST: E.ObjectLostError,
        OWNER_DIED: E.OwnerDiedError,
        GET_TIMEOUT: E.GetTimeoutError,
        CANCELLED: E.TaskCancelledError,
        UNKNOWN: E.RayTpuError,
    }


def exception_class_for(category: str) -> Type[BaseException]:
    """The public exception class a category surfaces as at ``get`` time."""
    return _exception_map().get(category, _exception_map()[UNKNOWN])


class FailureCause:
    """A categorized death cause. Renders as a string (so every site that
    used to store/print a free-text ``death_reason`` keeps working) and
    round-trips through :meth:`to_dict` / :meth:`from_value` for the wire."""

    __slots__ = ("category", "message", "context")

    def __init__(self, category: str, message: str = "",
                 **context: Any):
        self.category = category if category in CATEGORIES else UNKNOWN
        self.message = message
        # node_id / actor_id / task_id / worker_id / num_restarts / ...
        self.context = {k: v for k, v in context.items() if v is not None}

    def __str__(self) -> str:
        return self.message or self.category

    def __repr__(self) -> str:
        return f"FailureCause({self.category!r}, {self.message!r})"

    def to_dict(self) -> Dict[str, Any]:
        d = {"category": self.category, "message": self.message}
        d.update(self.context)
        return d

    @classmethod
    def from_value(cls, value: Any) -> "FailureCause":
        """Coerce wire dicts, plain strings (legacy reasons) and causes."""
        if isinstance(value, FailureCause):
            return value
        if isinstance(value, dict):
            d = dict(value)
            return cls(d.pop("category", UNKNOWN), d.pop("message", ""), **d)
        return cls(UNKNOWN, str(value or ""))


def cause_dict(category: str, message: str = "", **context: Any
               ) -> Dict[str, Any]:
    """Shorthand for the wire form (what rides RPC payloads + the store)."""
    return FailureCause(category, message, **context).to_dict()


def categorize_exception(exc: BaseException) -> str:
    """Best-effort category for an arbitrary exception (used where a raw
    exception crosses a kill/except site without a structured cause)."""
    from ray_tpu import exceptions as E

    if isinstance(exc, E.OutOfMemoryError):
        return OOM_KILL
    if isinstance(exc, E.WorkerCrashedError):
        return WORKER_CRASH
    if isinstance(exc, E.NodeDiedError):
        return NODE_DEATH
    if isinstance(exc, E.ActorUnschedulableError):
        return SCHEDULING_TIMEOUT
    if isinstance(exc, E.OwnerDiedError):
        return OWNER_DIED
    if isinstance(exc, E.ObjectLostError):
        return OBJECT_LOST
    if isinstance(exc, E.GetTimeoutError):
        return GET_TIMEOUT
    if isinstance(exc, E.TaskCancelledError):
        return CANCELLED
    if isinstance(exc, E.RuntimeEnvSetupError):
        return RUNTIME_ENV_SETUP
    if isinstance(exc, E.ActorDiedError):
        return ACTOR_RESTART_EXHAUSTED
    if isinstance(exc, E.TaskError):
        return TASK_ERROR
    return UNKNOWN


# ---- recovery pacing --------------------------------------------------------

def backoff_with_jitter(attempt: int, base_s: float, cap_s: float,
                        rng: Optional[random.Random] = None) -> float:
    """Capped exponential backoff with +-25% jitter — the one pacing
    function for every recovery loop (RPC reconnect re-dials, GCS
    restart-storm damping). ``attempt`` is 1-based; the uncapped delay
    doubles per attempt and the jitter keeps a fleet of reconnecting
    clients (or a gang of crash-looping actors) from re-dialing in
    lockstep."""
    delay = min(cap_s, base_s * (2 ** max(0, attempt - 1)))
    r = (rng.random() if rng is not None else random.random())
    return delay * (0.75 + 0.5 * r)


# ---- the one fire-and-forget emitter ---------------------------------------

class EmitLimiter:
    """Client-side rate limit for failure emission: at most one event per
    key per window. The GCS dedups *rows*; this caps the *RPCs* — a
    polling get() loop, a hot failing map, or a PG burst must not stream
    one GCS call per occurrence. Shared by every emitter so the window and
    prune logic have exactly one author."""

    def __init__(self, window_s: float = 30.0, cap: int = 512):
        self.window_s = window_s
        self.cap = cap
        self._last: Dict[Any, float] = {}

    def allow(self, key: Any) -> bool:
        now = time.monotonic()
        last = self._last.get(key)
        if last is not None and now - last < self.window_s:
            return False
        self._last[key] = now
        if len(self._last) > self.cap:
            cutoff = now - self.window_s
            kept = {k: t for k, t in self._last.items() if t > cutoff}
            if len(kept) > self.cap:
                # everything is inside the window (unique-key burst):
                # hard-cap to the newest half so the prune actually
                # shrinks — never O(n) rebuild per insert
                kept = dict(sorted(kept.items(),
                                   key=lambda kv: kv[1])[-self.cap // 2:])
            self._last = kept
        return True

def emit_raw(spawn: Callable, gcs, payload: Dict[str, Any],
             timeout: float = 10.0) -> None:
    """Ship one PRE-BUILT FailureEvent dict (chaos injections, recovery
    notices, drained buffers) without ever blocking or failing the caller
    — the raw-payload twin of :func:`emit`, so the wire send still has
    exactly one author."""
    async def _send():
        try:
            await gcs.call("failure_event", payload, timeout=timeout)
        except Exception:  # noqa: BLE001 — observability only
            pass

    try:
        spawn(_send())
    except Exception:  # noqa: BLE001 — teardown race
        pass


def emit(spawn: Callable, gcs, category: str, message: str,
         node_id: Optional[str] = None, timeout: float = 10.0,
         **fields: Any) -> None:
    """Ship one FailureEvent to the GCS ``failure_events`` store without
    ever blocking or failing the caller. Shared by every emitter (raylet,
    owner process, executing worker) so the wire shape has exactly one
    author. ``spawn`` is the site's coroutine launcher (``spawn_task`` on
    the raylet loop, ``io.spawn`` elsewhere); ``gcs`` anything with an
    async ``call``."""
    async def _send():
        try:
            msg: Dict[str, Any] = {"category": category, "message": message,
                                   "t": time.time()}
            if node_id is not None:
                msg["node_id"] = node_id
            msg.update({k: v for k, v in fields.items() if v is not None})
            await gcs.call("failure_event", msg, timeout=timeout)
        except Exception:  # noqa: BLE001 — observability only
            pass

    try:
        spawn(_send())
    except Exception:  # noqa: BLE001 — teardown race
        pass


# ---- Prometheus twin --------------------------------------------------------

_failures_counter = None


def observe_failure(category: str) -> None:
    """``rt_failures_total{category=}``: one increment per emitted failure
    event, in the emitting process's registry. Never raises — failure
    telemetry must not compound the failure it is recording."""
    global _failures_counter
    try:
        from ray_tpu.util import metrics as M

        if _failures_counter is None:
            _failures_counter = M.get_or_create(
                M.Counter, "rt_failures_total",
                "Failure events by death-cause category",
                tag_keys=("category",))
        _failures_counter.inc(1.0, {"category": category
                                    if category in CATEGORIES else UNKNOWN})
    except Exception:  # noqa: BLE001
        pass
