"""The ``@remote`` surface: RemoteFunction and the decorator itself.

Equivalent of the reference's ``python/ray/remote_function.py``
(``RemoteFunction :40``, ``_remote :257``) plus the decorator plumbing in
``python/ray/__init__.py``. A decorated function is exported to the function
table once (lazily) and invoked via small TaskSpecs thereafter.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

from ray_tpu.core.actor import ActorClass
from ray_tpu.core.task_spec import validate_options


class RemoteFunction:
    def __init__(self, fn: Callable, default_options: Dict[str, Any]):
        self._fn = fn
        self._default_options = validate_options(dict(default_options), for_actor=False)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__} cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._default_options)
        merged.update(validate_options(opts, for_actor=False))
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu.core.worker import global_worker

        return global_worker().submit_task(self._fn, self._default_options, args, kwargs)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    @property
    def underlying_function(self) -> Callable:
        return self._fn


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_tpus=1, ...)`` for functions and classes."""

    def make(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        if callable(target):
            return RemoteFunction(target, kwargs)
        raise TypeError(f"@remote target must be a function or class, got {target!r}")

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return make(args[0])
    if args:
        raise TypeError("@remote accepts only keyword options, e.g. @remote(num_tpus=1)")
    return make
