"""Actor API objects: ActorClass, ActorHandle, ActorMethod.

Equivalent of the reference's ``python/ray/actor.py`` (``ActorClass :384``,
``ActorHandle :1025``, ``ActorMethod :98``): a decorated class becomes an
ActorClass whose ``.remote()`` registers the actor with the control plane and
returns a handle; method calls on the handle submit ordered actor tasks
directly to the actor's worker. Handles are serializable and can be passed to
other tasks/actors.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.ids import ActorID
from ray_tpu.core.task_spec import validate_options


class ActorMethod:
    """Bound method wrapper exposing ``.remote()`` / ``.options()``."""

    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._submit(self._method_name, args, kwargs,
                                    num_returns=self._num_returns)

    def options(self, **opts):
        validate_options(opts, for_actor=False)
        for k in ("deadline_s", "on_overload"):
            if opts.get(k) is not None:
                raise ValueError(
                    f"option {k!r} is not supported on actor method "
                    "calls: actor tasks go straight to the actor's "
                    "worker and never sit in the raylet queue")
        handle, name = self._handle, self._method_name

        class _Opted:
            def remote(self, *args, **kwargs):
                return handle._submit(name, args, kwargs,
                                      num_returns=opts.get("num_returns", 1),
                                      name=opts.get("name"))

        return _Opted()

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly; use "
            f"actor.{self._method_name}.remote()."
        )


class ActorHandle:
    """A reference to a live actor; submits ordered tasks to it."""

    def __init__(self, actor_id: ActorID, class_name: str,
                 method_meta: Dict[str, int], original_handle: bool = False):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta  # method name -> default num_returns
        self._original_handle = original_handle

    @property
    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()

    def _submit(self, method_name: str, args: Tuple, kwargs: Dict,
                num_returns: int = 1, name: Optional[str] = None):
        from ray_tpu.core.worker import global_worker

        return global_worker().submit_actor_task(
            self._actor_id, method_name, args, kwargs, num_returns=num_returns)

    def __getattr__(self, item: str) -> ActorMethod:
        if item.startswith("_"):
            raise AttributeError(item)
        if self._method_meta and item not in self._method_meta:
            raise AttributeError(
                f"actor {self._class_name} has no method {item!r}")
        return ActorMethod(self, item, (self._method_meta or {}).get(item, 1))

    # -- serialization -------------------------------------------------------
    def _descriptor(self):
        return (self._actor_id.binary(), self._class_name, tuple(self._method_meta.items()))

    @classmethod
    def _rehydrate(cls, desc) -> "ActorHandle":
        return cls(ActorID(desc[0]), desc[1], dict(desc[2]))

    def __reduce__(self):
        return (ActorHandle._rehydrate, (self._descriptor(),))

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


def _method_metadata(cls: type) -> Dict[str, int]:
    meta: Dict[str, int] = {}
    for name, member in inspect.getmembers(cls, predicate=callable):
        if name.startswith("__") and name != "__call__":
            continue
        meta[name] = getattr(member, "_num_returns", 1)
    return meta


class ActorClass:
    """The product of ``@remote`` on a class."""

    def __init__(self, cls: type, default_options: Dict[str, Any]):
        self._cls = cls
        self._default_options = validate_options(dict(default_options), for_actor=True)
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._default_options)
        merged.update(validate_options(opts, for_actor=True))
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.core.worker import global_worker

        return global_worker().create_actor(
            self._cls, self._default_options, args, kwargs,
            method_meta=_method_metadata(self._cls))

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.dag_node import ClassNode

        return ClassNode(self, args, kwargs)

    @property
    def underlying_class(self) -> type:
        return self._cls


def method(*, num_returns: int = 1,
           concurrency_group: Optional[str] = None):
    """Per-method options decorator (reference: ``ray.method``).
    ``concurrency_group`` names one of the actor's declared
    ``concurrency_groups`` pools (reference: ConcurrencyGroupManager)."""

    def deco(fn):
        fn._num_returns = num_returns
        if concurrency_group is not None:
            fn._concurrency_group = concurrency_group
        return fn

    return deco
