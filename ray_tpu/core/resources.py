"""The resource algebra: predefined + custom resources, TPU first-class.

TPU-native redesign of the reference's resource model
(``src/ray/common/scheduling/``): there, CPU/GPU/memory are predefined C++
resources (``scheduling_ids.h:43-46``) and TPU is bolted on as a custom string
resource from Python (``_private/accelerator.py``). Here ``TPU`` is predefined
alongside CPU/memory, with per-instance accounting (which chip indices a task
holds → ``TPU_VISIBLE_CHIPS``) and topology labels (accelerator generation,
slice name/topology) carried on the node so slice-aware gang placement can be
expressed natively.

Quantities are fixed-point (10^-4 granularity) like the reference's
``FixedPoint`` (``fixed_point.h``) so fractional resources compare exactly.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

CPU = "CPU"
TPU = "TPU"
GPU = "GPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, TPU, GPU, MEMORY, OBJECT_STORE_MEMORY)

# Node labels describing TPU topology (reference analog: accelerator_type
# custom resources + GCE metadata probing in _private/accelerator.py:153-220).
LABEL_ACCELERATOR_TYPE = "accelerator-type"      # e.g. "TPU-V5P"
LABEL_SLICE_NAME = "tpu-slice-name"              # pod slice this host is in
LABEL_SLICE_TOPOLOGY = "tpu-slice-topology"      # e.g. "2x2x2"
LABEL_WORKER_ID_IN_SLICE = "tpu-worker-id"       # host index within the slice

GRANULARITY = 10000  # fixed-point denominator


def _fp(x: float) -> int:
    return round(x * GRANULARITY)


class ResourceSet:
    """An immutable bag of resource quantities (fixed-point internally)."""

    __slots__ = ("_q",)

    def __init__(self, quantities: Optional[Mapping[str, float]] = None):
        self._q: Dict[str, int] = {}
        for name, val in (quantities or {}).items():
            fv = _fp(val)
            if fv < 0:
                raise ValueError(f"negative resource {name}={val}")
            if fv:
                self._q[name] = fv

    @classmethod
    def _from_fp(cls, q: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._q = {k: v for k, v in q.items() if v}
        return rs

    def get(self, name: str) -> float:
        return self._q.get(name, 0) / GRANULARITY

    def names(self):
        return self._q.keys()

    def is_empty(self) -> bool:
        return not self._q

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._q.get(k, 0) >= v for k, v in self._q.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        q = dict(self._q)
        for k, v in other._q.items():
            q[k] = q.get(k, 0) + v
        return ResourceSet._from_fp(q)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        q = dict(self._q)
        for k, v in other._q.items():
            nv = q.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"resource {k} would go negative")
            q[k] = nv
        return ResourceSet._from_fp(q)

    def to_dict(self) -> Dict[str, float]:
        return {k: v / GRANULARITY for k, v in self._q.items()}

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSet) and other._q == self._q

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"


class NodeResources:
    """A node's total and available resources plus per-instance TPU state.

    Reference analog: ``NodeResources`` + ``ResourceInstanceSet``
    (``cluster_resource_data.h``) — the per-instance part is what lets a task
    holding ``num_tpus=2`` be pinned to specific chip indices.
    """

    def __init__(self, total: Mapping[str, float], labels: Optional[Mapping[str, str]] = None):
        self.total = ResourceSet(total)
        self.available = ResourceSet(total)
        self.labels: Dict[str, str] = dict(labels or {})
        n_tpu = int(self.total.get(TPU))
        self._free_tpu_chips: List[int] = list(range(n_tpu))

    def can_fit(self, req: ResourceSet) -> bool:
        return req.is_subset_of(self.available)

    def is_feasible(self, req: ResourceSet) -> bool:
        return req.is_subset_of(self.total)

    def allocate(self, req: ResourceSet) -> Dict[str, List[int]]:
        """Deduct ``req``; returns instance assignment (TPU chip indices)."""
        self.available = self.available.subtract(req)
        assignment: Dict[str, List[int]] = {}
        n_tpu = int(req.get(TPU))
        if n_tpu:
            if len(self._free_tpu_chips) < n_tpu:
                # undo and fail — should not happen if can_fit() was checked
                self.available = self.available.add(req)
                raise ValueError("TPU instance accounting out of sync")
            assignment[TPU] = self._free_tpu_chips[:n_tpu]
            del self._free_tpu_chips[:n_tpu]
        return assignment

    def release(self, req: ResourceSet, assignment: Optional[Dict[str, List[int]]] = None) -> None:
        self.available = self.available.add(req)
        if assignment and TPU in assignment:
            self._free_tpu_chips.extend(assignment[TPU])
            self._free_tpu_chips.sort()

    def utilization(self, req: ResourceSet) -> float:
        """Critical-resource utilization if ``req`` were placed here.

        Reference analog: the scorer inside ``HybridSchedulingPolicy``
        (``hybrid_scheduling_policy.h:29-48``).
        """
        util = 0.0
        after = self.available.subtract(req) if req.is_subset_of(self.available) else ResourceSet()
        for name in set(self.total.names()) | set(req.names()):
            tot = self.total.get(name)
            if tot <= 0:
                continue
            util = max(util, 1.0 - after.get(name) / tot)
        return util

    def to_dict(self) -> Dict:
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }
