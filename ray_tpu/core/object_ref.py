"""ObjectRef: a first-class future naming a value in the object plane.

Equivalent of the reference's ``ObjectRef`` (``python/ray/_raylet.pyx``): a
handle around a binary ObjectID plus an owner hint. Refs are hashable, can be
passed as arguments to remote calls (the runtime resolves them before
dispatch), can be awaited in async actors, and survive serialization via a
compact descriptor so ownership tracking sees every border crossing.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu.core import object_ledger


class ObjectRef:
    __slots__ = ("_id", "_owner", "_call_site", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None, call_site: str = ""):
        self._id = object_id
        self._owner = owner  # "host:port" of the owning worker, if known
        self._call_site = call_site
        if object_ledger.enabled():
            # ownership/reference ledger (`rt memory`): liveness of this ref
            # is tracked via a weakref, so dropping it needs no release call
            object_ledger.get_ledger().record_ref(self)

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def owner_address(self) -> Optional[str]:
        return self._owner

    def task_id(self):
        return self._id.task_id()

    # -- future protocol -----------------------------------------------------
    def __await__(self):
        from ray_tpu.core.worker import global_worker

        return global_worker().get_async(self).__await__()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        from ray_tpu.core.worker import global_worker

        return global_worker().as_future(self)

    # -- serialization -------------------------------------------------------
    def _descriptor(self) -> Tuple[bytes, Optional[str]]:
        return (self._id.binary(), self._owner)

    @classmethod
    def _rehydrate(cls, desc: Tuple[bytes, Optional[str]]) -> "ObjectRef":
        return cls(ObjectID(desc[0]), desc[1])

    def __reduce__(self):
        # Plain pickling (outside SerializationContext) keeps identity but
        # loses ownership registration — the context path is preferred.
        return (ObjectRef._rehydrate, (self._descriptor(),))

    # -- identity ------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"
