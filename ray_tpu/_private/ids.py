"""Binary unique IDs for every entity in the system.

TPU-native equivalent of the reference's ID scheme (``src/ray/common/id.h``
and ``src/ray/design_docs/id_specification.md``): fixed-width random IDs with
structural embedding — a TaskID embeds the job, an ObjectID embeds the task
that created it plus a return-index, an ActorID embeds the job. That embedding
is what makes ownership and lineage cheap to compute: given an ObjectID you
can recover its creating TaskID without a directory lookup.

Layout (bytes):
    JobID    = 4 random
    NodeID   = 16 random
    WorkerID = 16 random
    ActorID  = JobID + 8 random                      (12)
    TaskID   = ActorID(12) + 8 random                (20)
    ObjectID = TaskID(20) + 4 LE index               (24)
Normal (non-actor) tasks use a NIL actor suffix inside their TaskID.
"""

from __future__ import annotations

import os
import struct

JOB_ID_SIZE = 4
NODE_ID_SIZE = 16
WORKER_ID_SIZE = 16
ACTOR_ID_SIZE = JOB_ID_SIZE + 8
TASK_ID_SIZE = ACTOR_ID_SIZE + 8
OBJECT_ID_SIZE = TASK_ID_SIZE + 4
PLACEMENT_GROUP_ID_SIZE = 16


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self) -> int:
        return hash((type(self).__name__, self._bytes))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(struct.pack("<I", i))


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JOB_ID_SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        actor_part = job_id.binary() + b"\x00" * (ACTOR_ID_SIZE - JOB_ID_SIZE)
        return cls(actor_part + os.urandom(cls.SIZE - ACTOR_ID_SIZE))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(cls.SIZE - ACTOR_ID_SIZE))

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + b"\xff" * (cls.SIZE - ACTOR_ID_SIZE))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:ACTOR_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack("<I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Puts use the high bit of the index to avoid colliding with returns.
        return cls(task_id.binary() + struct.pack("<I", put_index | 0x80000000))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[TASK_ID_SIZE:])[0]
