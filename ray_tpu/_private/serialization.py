"""Serialization: cloudpickle in-band + pickle-5 out-of-band zero-copy buffers.

Equivalent of the reference's ``python/ray/_private/serialization.py``
(``SerializationContext``): values are cloudpickled with protocol 5 so large
contiguous buffers (numpy arrays, jax host arrays, bytes) are emitted
out-of-band and can live in shared memory without a copy on the read side.
ObjectRefs and ActorHandles found inside a value are swapped for lightweight
descriptors at pickle time and rehydrated at unpickle time, and the set of
contained refs is recorded so the owner can keep them alive (the reference's
contained-ref tracking, ``serialization.py:183-192``).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, List, Tuple

import cloudpickle

# Header layout of a serialized payload:
#   u32 metadata_len | metadata(pickled in-band bytes) | u32 nbuffers |
#   [u64 buffer_len | buffer bytes] * nbuffers
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class SerializedObject:
    """A serialized value: in-band pickle bytes + out-of-band buffers."""

    __slots__ = ("inband", "buffers", "contained_refs")

    def __init__(self, inband: bytes, buffers: List[memoryview], contained_refs):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        return (
            _U32.size * 2
            + len(self.inband)
            + sum(_U64.size + len(b) for b in self.buffers)
        )

    def write_to(self, buf: memoryview) -> int:
        """Pack into a contiguous writable buffer; returns bytes written."""
        off = 0
        buf[off : off + 4] = _U32.pack(len(self.inband)); off += 4
        buf[off : off + len(self.inband)] = self.inband; off += len(self.inband)
        buf[off : off + 4] = _U32.pack(len(self.buffers)); off += 4
        for b in self.buffers:
            n = len(b)
            buf[off : off + 8] = _U64.pack(n); off += 8
            buf[off : off + n] = b; off += n
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes())
        self.write_to(memoryview(out))
        return bytes(out)


def unpack_payload(buf: memoryview) -> Tuple[bytes, List[memoryview]]:
    """Split a packed payload back into (inband, buffers) with zero copies."""
    off = 0
    (n_inband,) = _U32.unpack(buf[off : off + 4]); off += 4
    inband = bytes(buf[off : off + n_inband]); off += n_inband
    (nbuf,) = _U32.unpack(buf[off : off + 4]); off += 4
    buffers: List[memoryview] = []
    for _ in range(nbuf):
        (n,) = _U64.unpack(buf[off : off + 8]); off += 8
        buffers.append(buf[off : off + n]); off += n
    return inband, buffers


class SerializationContext:
    """Per-worker serializer with ref/handle reducers.

    ``ref_reducer`` / ``ref_reconstructor`` are installed by the worker so that
    ObjectRefs and ActorHandles survive crossing process boundaries while the
    set of contained refs is captured for ownership accounting.
    """

    def __init__(self):
        self._custom_reducers: dict[type, Callable] = {}

    def register_reducer(self, typ: type, reducer: Callable) -> None:
        self._custom_reducers[typ] = reducer

    def serialize(self, value: Any) -> SerializedObject:
        buffers: List[memoryview] = []
        contained_refs: list = []

        # Import here to avoid a cycle at module load.
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.core.actor import ActorHandle

        class _Pickler(cloudpickle.Pickler):
            def reducer_override(self, obj):
                if isinstance(obj, ObjectRef):
                    contained_refs.append(obj)
                    return (ObjectRef._rehydrate, (obj._descriptor(),))
                if isinstance(obj, ActorHandle):
                    return (ActorHandle._rehydrate, (obj._descriptor(),))
                for typ, red in self_ctx._custom_reducers.items():
                    if isinstance(obj, typ):
                        return red(obj)
                # Delegate to cloudpickle's own handling (closures, lambdas,
                # locally-defined classes).
                return super().reducer_override(obj)

        self_ctx = self
        sink = io.BytesIO()
        pickler = _Pickler(sink, protocol=5, buffer_callback=lambda b: buffers.append(b.raw()))
        pickler.dump(value)
        return SerializedObject(sink.getvalue(), buffers, contained_refs)

    def deserialize(self, inband: bytes, buffers: List[memoryview]) -> Any:
        return pickle.loads(inband, buffers=buffers)

    def deserialize_payload(self, payload: memoryview) -> Any:
        inband, buffers = unpack_payload(payload)
        return self.deserialize(inband, buffers)
