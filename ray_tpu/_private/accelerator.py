"""TPU autodetection for node resource defaults.

Equivalent of the reference's ``python/ray/_private/accelerator.py``
(``_autodetect_num_tpus :153`` — counts ``/dev/accel*`` / vfio entries;
version probing via GCE metadata ``:175-220``). Metadata probing is omitted
(zero-egress environments); the generation can be supplied via env or labels.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional

TPU_VERSION_ENV = "RT_TPU_VERSION"          # e.g. "v5p", "v5e"
NUM_TPUS_ENV = "RT_NUM_TPUS"
SLICE_NAME_ENV = "RT_TPU_SLICE_NAME"
SLICE_TOPOLOGY_ENV = "RT_TPU_SLICE_TOPOLOGY"
WORKER_ID_ENV = "RT_TPU_WORKER_ID"


def autodetect_num_tpu_chips() -> int:
    if NUM_TPUS_ENV in os.environ:
        return int(os.environ[NUM_TPUS_ENV])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


# GKE's TPU device-plugin webhook injects these into TPU pods (the
# reference reads the analogous GKE env at ``ray_constants.py:488`` and GCE
# metadata via RAY_GCE_TPU_ACCELERATOR_ENDPOINT ``:494``). Mapping them
# here means a pod scheduled by the GKE provider registers with the same
# slice labels a TPU-VM node would — zero extra plumbing in node_main.
GKE_WORKER_ID_ENV = "TPU_WORKER_ID"
GKE_TOPOLOGY_ENV = "TPU_TOPOLOGY"
GKE_ACCELERATOR_ENV = "TPU_ACCELERATOR_TYPE"
GKE_SLICE_NAME_ENV = "TPU_NAME"


def gke_node_labels() -> Dict[str, str]:
    """Slice labels from GKE-injected pod env (empty off-GKE)."""
    from ray_tpu.core import resources as res

    labels: Dict[str, str] = {}
    if GKE_ACCELERATOR_ENV in os.environ:
        labels[res.LABEL_ACCELERATOR_TYPE] = (
            "TPU-" + os.environ[GKE_ACCELERATOR_ENV].split("-")[0].upper())
    if GKE_SLICE_NAME_ENV in os.environ:
        labels[res.LABEL_SLICE_NAME] = os.environ[GKE_SLICE_NAME_ENV]
    if GKE_TOPOLOGY_ENV in os.environ:
        labels[res.LABEL_SLICE_TOPOLOGY] = os.environ[GKE_TOPOLOGY_ENV]
    if GKE_WORKER_ID_ENV in os.environ:
        labels[res.LABEL_WORKER_ID_IN_SLICE] = os.environ[GKE_WORKER_ID_ENV]
    return labels


def tpu_node_labels() -> Dict[str, str]:
    from ray_tpu.core import resources as res

    labels: Dict[str, str] = gke_node_labels()
    version = os.environ.get(TPU_VERSION_ENV)
    if version:
        labels[res.LABEL_ACCELERATOR_TYPE] = f"TPU-{version.upper()}"
    if SLICE_NAME_ENV in os.environ:
        labels[res.LABEL_SLICE_NAME] = os.environ[SLICE_NAME_ENV]
    if SLICE_TOPOLOGY_ENV in os.environ:
        labels[res.LABEL_SLICE_TOPOLOGY] = os.environ[SLICE_TOPOLOGY_ENV]
    if WORKER_ID_ENV in os.environ:
        labels[res.LABEL_WORKER_ID_IN_SLICE] = os.environ[WORKER_ID_ENV]
    return labels


def set_visible_chips(chip_indices, env: Optional[dict] = None) -> None:
    """Pin a worker process to specific chips (reference:
    ``TPU_VISIBLE_CHIPS`` handling, ``ray_constants.py:407``,
    ``worker.py:430`` — the TPU analog of CUDA_VISIBLE_DEVICES)."""
    from ray_tpu._private.config import get_config

    target = env if env is not None else os.environ
    target[get_config().tpu_visible_chips_env] = ",".join(str(i) for i in chip_indices)
