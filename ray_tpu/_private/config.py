"""Env-overridable configuration flags.

TPU-native equivalent of the reference's ``RayConfig`` flag system
(``src/ray/common/ray_config_def.h`` — 207 ``RAY_CONFIG(type, name, default)``
entries, each overridable via a ``RAY_<name>`` env var). Here every flag is a
typed attribute on :class:`Config`, overridable via ``RT_<NAME>`` env vars, and
a single immutable snapshot is taken at import so all processes of a session
see consistent values (the snapshot is also serialized to spawned workers).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

_ENV_PREFIX = "RT_"


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclasses.dataclass
class Config:
    """All runtime flags. Override any field with ``RT_<UPPERCASE_NAME>``."""

    # ---- session / process tree -------------------------------------------
    session_dir_root: str = "/tmp/ray_tpu"
    # Interface every RPC server binds ("" = loopback). Multi-host clusters
    # set 0.0.0.0 (rt start --host); servers then advertise the machine's
    # outbound IP so cross-host peers dial a reachable address.
    bind_host: str = ""
    head_port: int = 0  # 0 = pick a free port
    node_manager_port: int = 0
    num_workers_soft_limit: int = 0  # 0 = num_cpus of the node
    # idle pooled workers beyond the soft limit are reaped after this long
    # (reference: idle worker killing in the raylet worker pool) — bounds
    # process growth when jobs cycle through many runtime envs
    idle_worker_ttl_s: float = 120.0
    worker_register_timeout_s: float = 30.0
    process_startup_timeout_s: float = 30.0
    # Extra startup budget for workers that must materialize a runtime env
    # before announcing ready (pip installs can dwarf plain process spawn).
    runtime_env_setup_timeout_s: float = 600.0
    graceful_shutdown_timeout_s: float = 5.0

    # ---- scheduling --------------------------------------------------------
    # Hybrid policy knobs (reference: raylet/scheduling/policy/
    # hybrid_scheduling_policy.h:29-48): prefer available nodes, rank by
    # critical-resource utilization, spill above this threshold.
    scheduler_spread_threshold: float = 0.5
    scheduler_top_k_fraction: float = 0.2
    lease_timeout_s: float = 10.0
    max_pending_lease_requests_per_key: int = 10
    # A queued-but-not-running task waits this long before the raylet asks
    # the GCS for another node with free capacity (load-based spillback,
    # reference: ScheduleAndDispatchTasks spillback). Bounded hops.
    spillback_delay_s: float = 0.1
    spillback_max_hops: int = 2
    # ---- overload robustness (fair dispatch / admission / deadlines) -------
    # Admission control (reference: the raylet rejecting leases under
    # backlog pressure): each scheduling class's raylet queue is bounded;
    # a submit beyond the bound is bounced with a ``backpressure`` reply
    # that the owner blocks-with-backoff on by default (fail-fast is a
    # per-task opt-in via ``.options(on_overload="fail")``). 0 = unbounded.
    max_queued_per_class: int = 20000
    # Owner-side pacing between backpressured resubmits (capped
    # exponential + jitter via failure.backoff_with_jitter).
    backpressure_retry_base_s: float = 0.05
    backpressure_retry_max_s: float = 2.0
    # Warm worker pool (reference: ``worker_pool.h`` prestart): keep this
    # many plain (no-chip, no-runtime-env) workers idle so cold task
    # dispatch and actor creation stop paying interpreter boot. 0 = off.
    worker_prestart_floor: int = 0
    # Actor creation adopts an idle pooled worker instead of forking a
    # fresh interpreter when the actor needs no TPU chips and no runtime
    # env (the 0.4/s spawn floor of SCALE_r05 was pure process boot).
    worker_adopt_for_actors: bool = True
    # Raylet->GCS task-event chatter coalesces into one batched flush per
    # interval instead of one RPC per state change on the submit hot path.
    task_event_flush_s: float = 0.1

    # ---- object store ------------------------------------------------------
    # Objects <= this many bytes are stored in the owner's in-process memory
    # store and travel inline in RPCs (reference: core_worker memory store).
    max_direct_call_object_size: int = 100 * 1024
    object_store_memory_bytes: int = 0  # 0 = 30% of system memory, capped
    object_store_default_cap_bytes: int = 2 * 1024**3
    object_transfer_chunk_bytes: int = 8 * 1024**2
    object_spilling_dir: str = ""  # "" = <session_dir>/spill
    object_spill_threshold: float = 0.8
    # ---- memory observability ----------------------------------------------
    # Ownership/reference ledger (reference: the core worker's
    # ReferenceCounter behind `ray memory`): per-process table of owned
    # objects with owner, size, state and ref kinds, aggregated by
    # memory_summary() / `rt memory`. Off = zero bookkeeping per ObjectRef.
    object_ledger: bool = True
    # Capture the Python call site that created each ref (Ray parity:
    # RAY_record_ref_creation_sites). Costs a stack walk per ref — opt-in.
    record_ref_creation_sites: bool = False
    # An owned object older than this whose only references are local refs
    # in the driver is flagged as a leak suspect by memory_summary().
    memory_leak_age_s: float = 300.0

    # ---- health / fault tolerance -----------------------------------------
    heartbeat_interval_s: float = 1.0
    node_death_timeout_s: float = 10.0
    # Overall bound on waiting for a PENDING_CREATION/RESTARTING actor to
    # come alive before an actor call fails with ActorUnschedulableError.
    # 0 = wait forever (reference semantics). Callers needing bounded
    # resolution (health checks, CI) set RT_ACTOR_RESOLVE_DEADLINE_S.
    actor_resolve_deadline_s: float = 0.0
    actor_restart_backoff_s: float = 0.5
    # Restart-storm damping (reference: exponential actor restart delays in
    # gcs_actor_manager): the GCS backs off min(cap, base * 2**(n-1)) +-25%
    # jitter per consecutive restart, so a crash-looping actor can't hammer
    # the scheduler at a fixed cadence.
    actor_restart_backoff_max_s: float = 30.0
    # RpcClient auto-reconnect pacing: capped exponential backoff + jitter
    # across up to rpc_reconnect_attempts re-dials per call (a head restart
    # takes a moment to rebind — an immediate single re-dial just loses).
    rpc_reconnect_base_s: float = 0.05
    rpc_reconnect_max_s: float = 2.0
    rpc_reconnect_attempts: int = 5
    task_max_retries_default: int = 3
    # OOM prevention (reference: common/memory_monitor.h +
    # raylet/worker_killing_policy.cc): when node memory use crosses the
    # threshold, the raylet kills a worker (retriable task workers first,
    # largest RSS) instead of letting the kernel OOM-killer nuke the raylet.
    # >= 1.0 disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_interval_s: float = 1.0

    # ---- gcs ---------------------------------------------------------------
    gcs_rpc_timeout_s: float = 30.0
    pubsub_poll_timeout_s: float = 30.0
    # fsync the KV WAL before acking each kv_put. Default off: appends are
    # flushed (process-crash durable) but only fsynced at migration and
    # shutdown, so a host crash can lose acked puts. Turn on for host-crash
    # durability at the cost of per-put fsync latency.
    wal_fsync: bool = False

    # ---- TPU / accelerator -------------------------------------------------
    # Chips per TPU-VM host (v4/v5p hosts expose 4 chips; v5e hosts 1/4/8).
    tpu_chips_per_host: int = 4
    tpu_visible_chips_env: str = "TPU_VISIBLE_CHIPS"
    coordinator_port: int = 0

    # ---- logging / metrics -------------------------------------------------
    log_to_driver: bool = True
    event_buffer_size: int = 10000
    metrics_report_interval_s: float = 5.0

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                setattr(cfg, f.name, _coerce(os.environ[env_key], f.type if isinstance(f.type, type) else type(getattr(cfg, f.name))))
        return cfg

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, data: str) -> "Config":
        cfg = cls()
        for k, v in json.loads(data).items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
        return cfg


_config: Config | None = None


def get_config() -> Config:
    """The process-wide config snapshot (lazy; env read once)."""
    global _config
    if _config is None:
        env_blob = os.environ.get("RT_CONFIG_JSON")
        _config = Config.from_json(env_blob) if env_blob else Config.from_env()
    return _config


def set_config(cfg: Config) -> None:
    global _config
    _config = cfg


def reset_config_for_tests() -> None:
    """Drop the cached snapshot so the next get_config() re-reads the env."""
    global _config
    _config = None
