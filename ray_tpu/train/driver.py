"""StepDriver: the fused-K product training fast path.

The promotion ROADMAP item 2 asks for: ``make_multi_step``'s ``lax.scan``
fusion (bench-proved launch amortization — PR 9's decode-side ``step_many``
is the same Podracer/Anakin discipline, arxiv 2104.06272) becomes the
Train layer's configured step driver instead of a bench-only instrument.

One driver owns the whole step path:

- **K-fused launches**: ``steps_per_launch`` batches stack into one
  [K, B, ...] tree and ONE compiled program executes K optimizer steps
  back-to-back on-device (host dispatch paid once per K).
- **Graceful degrade**: the 1f1b pipeline schedule (no scan support) and
  ragged tails (fewer than K batches left) fall back to the single-step
  program — loss/param-exact either way, machine-asserted in
  ``tests/test_zz_train_fast.py``.
- **Plan-carried shardings**: both programs compile through the same
  :class:`~ray_tpu.parallel.plan.Plan`, and batch placement reuses its
  cached NamedShardings (no per-call re-derivation).
- **Compute-limited accounting**: the driver splits loop wall into host
  (batch pull + stack + place) vs step (dispatch + on-device) time and
  publishes ``rt_train_steps_per_launch`` / ``rt_train_host_overhead_ratio``
  so "is the orchestration touching the gradient path?" is a metric, not
  a bench archaeology project.
- **Flight recorder**: every fused-K launch stamps its phase walls
  {data_wait, h2d, dispatch, device_compute, host_tax, compile} plus
  K/tokens/shape/analytic-FLOPs into :class:`~ray_tpu.util.train_recorder.
  TrainRecorder` (``self.recorder``) — device-done lands via an async
  done-hook on the launch's metrics buffers, never a ``block_until_ready``
  on the step path. ``RT_TRAIN_RECORDER=0`` reduces this to one predicate
  per launch.

The K knob comes from ``FastPathConfig.steps_per_launch``
(``RunConfig.fast_path``) when the driver is built inside a
``train_loop_per_worker``; standalone callers pass it explicitly.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ray_tpu.util import metrics as M

_LAUNCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


def _instruments():
    return (
        M.get_or_create(M.Histogram, "rt_train_steps_per_launch",
                        "Optimizer steps fused into one device launch by "
                        "the train StepDriver",
                        boundaries=_LAUNCH_BUCKETS),
        M.get_or_create(M.Gauge, "rt_train_host_overhead_ratio",
                        "Host-side fraction of the StepDriver loop (batch "
                        "pull/stack/place + report handoff vs compiled "
                        "step time)"),
    )


class StepDriver:
    """Drives (params, opt_state) through a stream of batches, K steps per
    compiled launch.

    ``batches`` may yield per-step host batches (dict leaves shaped
    [B, ...] — the driver stacks K of them) or pre-stacked [k, B, ...]
    trees from ``iter_jax_batches(stack=K)`` (the iterator advertises via
    its ``stack`` attribute). Anything with ``k == steps_per_launch`` runs
    fused; smaller tails run step-by-step through the single-step program.
    """

    def __init__(self, cfg: Any, optimizer: Any, *,
                 mesh: Any = None, loss_fn: Optional[Callable] = None,
                 steps_per_launch: Optional[int] = None,
                 plan: Any = None):
        from ray_tpu.parallel import train_step as ts

        if steps_per_launch is None:
            from ray_tpu.train.session import get_fast_path

            steps_per_launch = get_fast_path().steps_per_launch
        self.requested_steps_per_launch = steps_per_launch
        self.fused = steps_per_launch > 1 and ts.supports_multi_step(cfg)
        self.steps_per_launch = steps_per_launch if self.fused else 1
        if mesh is not None and plan is None:
            from ray_tpu.parallel.plan import compile_plan

            plan = compile_plan(cfg, mesh)
        self.plan = plan
        self.cfg = cfg
        self._mesh = mesh
        self._ts = ts
        # the training flight recorder (PR 20): per-launch phase records,
        # launch-gap accounting and the MFU-gap waterfall — only the fused
        # path stamps it, so single-step drivers carry a dormant recorder
        try:
            from ray_tpu.util.train_recorder import TrainRecorder

            self.recorder: Optional[Any] = TrainRecorder()
        except Exception:  # noqa: BLE001 — observability must not block
            self.recorder = None
        self._fpt_cache: Dict[int, float] = {}
        self._single = ts.make_train_step(cfg, optimizer, loss_fn, mesh,
                                          plan=plan)
        self._multi = (ts.make_multi_step(cfg, optimizer,
                                          self.steps_per_launch, loss_fn,
                                          mesh, plan=plan)
                       if self.fused else None)
        self.launches = 0
        self.steps = 0
        self.host_s = 0.0
        self.step_s = 0.0
        # (params, opt_state) AFTER the latest launch — what an on_launch
        # checkpoint must serialize (the pre-launch trees were donated into
        # the launch and their buffers are gone)
        self.state: Optional[Tuple[Any, Any]] = None
        self._hist, self._gauge = _instruments()

    # ---- introspection ------------------------------------------------------
    def compile_count(self) -> int:
        """jit-cache entries of the ACTIVE fused program — the PR 12-style
        single-launch assertion (K steps, one executable, forever 1)."""
        fn = self._multi if self._multi is not None else self._single
        return int(fn._jit._cache_size())

    def host_overhead_ratio(self) -> float:
        total = self.host_s + self.step_s
        return (self.host_s / total) if total > 0 else 0.0

    def reset_attribution(self) -> None:
        """Zero the host/step wall accounting (call after warmup so the
        reported ratio describes the steady state, not compile time).
        Launch/step counters are left alone — callers diff those."""
        self.host_s = 0.0
        self.step_s = 0.0

    def report(self) -> Dict[str, Any]:
        """Loop-side attribution (the ``rt_train_*`` series, as a dict)."""
        return {
            "steps": self.steps,
            "launches": self.launches,
            "steps_per_launch": self.steps_per_launch,
            "host_s": round(self.host_s, 4),
            "step_s": round(self.step_s, 4),
            "host_overhead_ratio": round(self.host_overhead_ratio(), 4),
        }

    # ---- batch plumbing -----------------------------------------------------
    def _place(self, batch: Any, stacked: bool) -> Any:
        if self.plan is None:
            return batch
        return self.plan.place_batch(batch, stacked=stacked)

    @staticmethod
    def _stack(batches: List[Dict[str, Any]]) -> Dict[str, Any]:
        import numpy as np

        import jax

        return jax.tree.map(lambda *xs: np.stack(xs), *batches)

    @staticmethod
    def _lead(batch: Any) -> int:
        import jax

        leaves = jax.tree.leaves(batch)
        return leaves[0].shape[0] if leaves else 0

    def _launch_meta(self, batch: Any) -> Tuple[int, int, Tuple[int, ...]]:
        """(tokens, seq, lead-leaf shape) of a stacked batch — the
        recorder's FLOPs join reads these (shape inspection only, no
        device sync)."""
        import jax

        leaves = jax.tree.leaves(batch)
        shape = tuple(int(d) for d in leaves[0].shape) if leaves else ()
        tokens, seq = self._ts._batch_tokens(batch, stacked=True)
        return tokens, seq, shape

    def _launch_flops(self, tokens: int, seq: int) -> float:
        """Analytic FLOPs for one fused launch via ``util.flops`` —
        per-token cost cached per seq length (custom-loss configs without
        transformer geometry record launches without an MFU join)."""
        if tokens <= 0:
            return 0.0
        fpt = self._fpt_cache.get(seq)
        if fpt is None:
            try:
                from ray_tpu.util import flops as F

                fpt = float(F.train_flops_per_token(self.cfg, seq))
            except Exception:  # noqa: BLE001 — non-transformer cfg
                fpt = 0.0
            self._fpt_cache[seq] = fpt
        return tokens * fpt

    # ---- the loop -----------------------------------------------------------
    def run(self, params: Any, opt_state: Any, batches: Iterable[Any],
            on_launch: Optional[Callable[[Dict[str, Any]], None]] = None,
            stacked: Optional[bool] = None
            ) -> Tuple[Any, Any, Optional[Dict[str, Any]]]:
        """Drive the whole iterator; returns (params, opt_state, metrics of
        the last launch — leaves stay on-device; each fused metrics leaf is
        a [k] per-step array). ``on_launch`` fires once per device launch
        with those metrics (hand them to ``session.report`` — coercion is
        the drainer's job, not the loop's). ``stacked`` overrides the
        pre-stacked autodetection (``batches.stack``) for wrappers that
        lose the attribute."""
        prestacked = (getattr(batches, "stack", 1) > 1 if stacked is None
                      else stacked)
        K = self.steps_per_launch
        adv = getattr(batches, "stack", None)
        if prestacked and self.fused and adv is not None and adv != K:
            raise ValueError(
                f"iterator stacks {adv} batches per group but the driver "
                f"fuses {K} steps per launch — every group would silently "
                f"degrade to single-step; use iter_jax_batches(stack={K})")
        last_metrics: Optional[Dict[str, Any]] = None
        pend: List[Dict[str, Any]] = []
        it = iter(batches)
        rec = self.recorder if (self.recorder is not None
                                and self.recorder.enabled
                                and self.fused) else None
        rec_data_s = 0.0  # data_wait accumulated toward the pending launch
        rec_t0: Optional[float] = None  # epoch start of its wall
        while True:
            if rec is not None and rec_t0 is None:
                rec_t0 = time.time()
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                batch = None
            if rec is not None and batch is not None:
                rec_data_s += time.perf_counter() - t0
            if batch is not None and not prestacked and K > 1:
                pend.append(batch)
                if len(pend) < K:
                    self.host_s += time.perf_counter() - t0
                    continue
                t_stack = time.perf_counter()
                batch, pend = self._stack(pend), []
                if rec is not None:
                    # the K-batch np.stack is the loader's wall too
                    rec_data_s += time.perf_counter() - t_stack
                stacked = True
            elif batch is not None:
                stacked = prestacked and self._lead(batch) >= 1
            if batch is None:
                # ragged tail of a self-stacked run: fewer than K batches
                # left — single-step them
                tail, pend = pend, []
                for b in tail:
                    params, opt_state, last_metrics = self._run_single(
                        params, opt_state, b, t_host0=t0, on_launch=on_launch)
                    t0 = time.perf_counter()
                self.host_s += time.perf_counter() - t0
                break
            if stacked and self._lead(batch) == K and self._multi is not None:
                if rec is not None:
                    data_ready_t = time.time()  # stacked batch in hand
                    tokens, seq_len, shape = self._launch_meta(batch)
                t_h2d = time.perf_counter()
                placed = self._place(batch, stacked=True)
                h2d_s = time.perf_counter() - t_h2d
                self.host_s += time.perf_counter() - t0
                n_exec = self.compile_count() if rec is not None else 0
                t1 = time.perf_counter()
                params, opt_state, metrics = self._multi(
                    params, opt_state, placed)
                dispatch_s = time.perf_counter() - t1
                t_disp_end = time.time() if rec is not None else 0.0
                self.step_s += dispatch_s
                self.launches += 1
                self.steps += K
                self._observe(K)
                last_metrics = metrics
                self.state = (params, opt_state)
                seq = 0
                if rec is not None:
                    # a call that grew the jit cache spent its wall
                    # tracing+compiling — book it as compile, not dispatch
                    # (step-profiler convention, so the two can't drift)
                    compiled = self.compile_count() > n_exec
                    seq = rec.record_launch(
                        t_start=rec_t0, data_wait_s=rec_data_s,
                        h2d_s=h2d_s,
                        dispatch_s=0.0 if compiled else dispatch_s,
                        compile_s=dispatch_s if compiled else 0.0,
                        data_ready_t=data_ready_t,
                        t_dispatch_end=t_disp_end, k=K, tokens=tokens,
                        batch_shape=shape,
                        flops=self._launch_flops(tokens, seq_len))
                    # async done-hook: the watcher blocks on the METRICS
                    # leaves (never the donated params) off the step path
                    rec.watch_outputs(seq, metrics)
                    rec_data_s, rec_t0 = 0.0, None
                if on_launch is not None:
                    # callback work (report handoff, checkpoint snapshot
                    # dispatch) is host-side loop time — attribute it
                    tc = time.perf_counter()
                    on_launch(metrics)
                    tax = time.perf_counter() - tc
                    self.host_s += tax
                    if rec is not None and seq:
                        rec.add_host_tax(seq, tax)
            elif stacked:
                # pre-stacked ragged tail (k < K, or any stacked input
                # once the driver degraded to K=1) — slice and single-step
                rec_data_s, rec_t0 = 0.0, None
                import jax

                k = self._lead(batch)
                if self.fused and k > K:
                    # a tail group is always SMALLER than K; a bigger one
                    # means the feed stacks more than the driver fuses and
                    # launch amortization would silently turn off — refuse
                    raise ValueError(
                        f"stacked group of {k} batches exceeds "
                        f"steps_per_launch {K}: the feed's stacking does "
                        f"not match the driver's fusion factor")
                self.host_s += time.perf_counter() - t0
                for i in range(k):
                    b = jax.tree.map(lambda x, idx=i: x[idx], batch)
                    params, opt_state, last_metrics = self._run_single(
                        params, opt_state, b, on_launch=on_launch)
            else:
                rec_data_s, rec_t0 = 0.0, None
                params, opt_state, last_metrics = self._run_single(
                    params, opt_state, batch, t_host0=t0,
                    on_launch=on_launch)
        self._gauge.set(self.host_overhead_ratio())
        return params, opt_state, last_metrics

    def _run_single(self, params, opt_state, batch, *, t_host0=None,
                    on_launch=None):
        t0 = t_host0 if t_host0 is not None else time.perf_counter()
        placed = self._place(batch, stacked=False)
        self.host_s += time.perf_counter() - t0
        t1 = time.perf_counter()
        params, opt_state, metrics = self._single(params, opt_state, placed)
        self.step_s += time.perf_counter() - t1
        self.launches += 1
        self.steps += 1
        self._observe(1)
        self.state = (params, opt_state)
        if on_launch is not None:
            tc = time.perf_counter()
            on_launch(metrics)
            self.host_s += time.perf_counter() - tc
        return params, opt_state, metrics

    def _observe(self, k: int) -> None:
        try:
            self._hist.observe(float(k))
            if self.launches % 8 == 0:
                self._gauge.set(self.host_overhead_ratio())
        except Exception:  # noqa: BLE001 — telemetry must not fail the step
            pass
