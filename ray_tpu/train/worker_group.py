"""The gang of training worker actors.

Reference analog: ``WorkerGroup`` (``train/_internal/worker_group.py:101``)
of ``RayTrainWorker`` actors + the gang placement logic of
``BackendExecutor._create_placement_group`` (``backend_executor.py:166``).
Workers are placed one-per-bundle in a placement group shaped by the
ScalingConfig (a slice group for multi-host TPU gangs).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainSession
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
    slice_group,
)


class TrainWorker:
    """Actor hosting one rank of the training gang."""

    def __init__(self, rank: int, world_size: int, experiment_name: str):
        self.rank = rank
        self.world_size = world_size
        self.experiment_name = experiment_name
        self.session: Optional[TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    def ping(self) -> int:
        return self.rank

    def bootstrap_jax_distributed(self, group_name: str) -> None:
        from ray_tpu.collective import bootstrap_jax_distributed

        bootstrap_jax_distributed(self.world_size, self.rank, group_name)

    def bootstrap_torch_distributed(self, group_name: str) -> None:
        from ray_tpu.collective.rendezvous import bootstrap_torch_distributed

        bootstrap_torch_distributed(self.world_size, self.rank, group_name)

    def start(self, train_fn: Callable, config: Dict[str, Any],
              checkpoint: Optional[Checkpoint],
              dataset_shards: Optional[Dict[str, Any]],
              fast_path=None) -> None:
        ctx = TrainContext(self.rank, self.world_size,
                           experiment_name=self.experiment_name)
        self.session = TrainSession(ctx, checkpoint=checkpoint,
                                    dataset_shards=dataset_shards,
                                    fast_path=fast_path)
        session_mod.init_session(self.session)

        def run():
            try:
                if _takes_config(train_fn):
                    train_fn(config)
                else:
                    train_fn()
                self.session.finish()
            except BaseException as e:  # noqa: BLE001
                traceback.print_exc()
                self.session.finish(error=e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"rt-train-rank{self.rank}")
        self._thread.start()

    def next_result(self) -> Dict[str, Any]:
        """Blocks until the worker reports, finishes, or errors."""
        item = self.session.results.get()
        if item["type"] == "error":
            err = item["error"]
            return {"type": "error", "message": repr(err),
                    "traceback": "".join(traceback.format_exception(
                        type(err), err, err.__traceback__))}
        return item

    def shutdown(self) -> None:
        session_mod.clear_session()


def _takes_config(fn: Callable) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return True


class RoleGroup:
    """A heterogeneous gang: one NAMED role actor per placement-group
    bundle (the RLHF shape: policy learner / reference / reward /
    generation engine placed together, reference arxiv 2312.11819's
    adaptive placement).

    Unlike :class:`WorkerGroup` (N identical ranks running one train
    fn), each role brings its own actor class, resources and ctor args.
    The group reserves ONE placement group shaped by the roles' bundles,
    so the whole pipeline lands atomically (or not at all), and
    ``describe()`` reports which bundle each role occupies — the
    placement story ``rt trace`` shows when the creating driver runs
    under a span (`RLHFPipeline` enables tracing around ``start()`` so
    every ``<Role>.__init__`` + readiness ping becomes a span).
    """

    def __init__(self, name: str, strategy: str = "PACK"):
        self.name = name
        self.strategy = strategy
        self.pg = None
        self.actors: Dict[str, Any] = {}
        self._roles: List[Dict[str, Any]] = []

    def add_role(self, role: str, actor_cls: type, *args,
                 num_cpus: float = 1, options: Optional[Dict] = None,
                 **kwargs) -> "RoleGroup":
        """Declare one role (call before ``start``); chainable."""
        if any(r["role"] == role for r in self._roles):
            raise ValueError(f"duplicate role {role!r}")
        self._roles.append({"role": role, "cls": actor_cls, "args": args,
                            "kwargs": kwargs, "num_cpus": num_cpus,
                            "options": dict(options or {})})
        return self

    def start(self, timeout: float = 300) -> None:
        if not self._roles:
            raise ValueError("no roles declared")
        bundles = [{"CPU": r["num_cpus"]} for r in self._roles]
        self.pg = placement_group(bundles, strategy=self.strategy,
                                  name=self.name)
        if not self.pg.wait(timeout=timeout):
            remove_placement_group(self.pg)
            self.pg = None
            raise TimeoutError(
                f"role group {self.name!r}: could not reserve {bundles}")
        try:
            for i, r in enumerate(self._roles):
                opts = dict(r["options"])
                opts.setdefault("num_cpus", r["num_cpus"])
                opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(self.pg, i)
                handle = ray_tpu.remote(r["cls"]).options(**opts).remote(
                    *r["args"], **r["kwargs"])
                self.actors[r["role"]] = handle
            # readiness barrier: every role constructed (and its span
            # recorded) before the pipeline starts issuing phases
            ray_tpu.get([a.ping.remote() for a in self.actors.values()],
                        timeout=timeout)
        except BaseException:
            self.shutdown()
            raise

    def __getitem__(self, role: str):
        return self.actors[role]

    def describe(self) -> List[Dict[str, Any]]:
        """role -> bundle placement (the `rt trace` companion table)."""
        return [{"role": r["role"], "bundle_index": i,
                 "num_cpus": r["num_cpus"],
                 "actor": type(r["cls"]).__name__
                 if not isinstance(r["cls"], type) else r["cls"].__name__}
                for i, r in enumerate(self._roles)]

    def shutdown(self) -> None:
        for handle in self.actors.values():
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.actors = {}
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self.pg = None


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, experiment_name: str):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.pg = None
        self.workers: List = []

    def start(self) -> None:
        n = self.scaling.num_workers
        if self.scaling.use_tpu and not self.scaling.resources_per_worker:
            # Multi-host TPU gang: the pod-slice PG shape (slice_group —
            # one bundle per host, chips pinned per bundle). A one-host
            # gang packs; a multi-host gang takes the ScalingConfig's
            # strategy (topology="v5p-N" already set STRICT_SPREAD).
            self.pg = slice_group(
                num_hosts=n,
                chips_per_host=self.scaling.tpu_chips_per_worker,
                cpus_per_host=self.scaling.cpus_per_worker,
                strategy=(self.scaling.placement_strategy if n > 1
                          else "PACK"),
                name=self.experiment_name)
        else:
            self.pg = placement_group(
                [self.scaling.bundle() for _ in range(n)],
                strategy=self.scaling.placement_strategy)
        if not self.pg.wait(timeout=300):
            remove_placement_group(self.pg)
            raise TimeoutError(
                f"could not reserve {n} x {self.scaling.bundle()} "
                f"(placement group timed out)")
        try:
            actor_cls = ray_tpu.remote(TrainWorker)
            bundle = self.scaling.bundle()
            self.workers = [
                actor_cls.options(
                    num_cpus=bundle.get("CPU", 1),
                    num_tpus=bundle.get("TPU", 0) or None,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, i),
                ).remote(i, n, self.experiment_name)
                for i in range(n)
            ]
            ray_tpu.get([w.ping.remote() for w in self.workers], timeout=300)
        except BaseException:
            # Don't leak the gang's reservation on a failed start.
            self.shutdown()
            raise

    def run_async(self, method: str, *args) -> List:
        return [getattr(w, method).remote(*args) for w in self.workers]

    def run(self, method: str, *args, timeout: Optional[float] = None) -> List:
        return ray_tpu.get(self.run_async(method, *args), timeout=timeout)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
        self.workers = []
        self.pg = None
