"""The gang of training worker actors.

Reference analog: ``WorkerGroup`` (``train/_internal/worker_group.py:101``)
of ``RayTrainWorker`` actors + the gang placement logic of
``BackendExecutor._create_placement_group`` (``backend_executor.py:166``).
Workers are placed one-per-bundle in a placement group shaped by the
ScalingConfig (a slice group for multi-host TPU gangs).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, TrainSession
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
    slice_group,
)


class TrainWorker:
    """Actor hosting one rank of the training gang."""

    def __init__(self, rank: int, world_size: int, experiment_name: str):
        self.rank = rank
        self.world_size = world_size
        self.experiment_name = experiment_name
        self.session: Optional[TrainSession] = None
        self._thread: Optional[threading.Thread] = None

    def ping(self) -> int:
        return self.rank

    def bootstrap_jax_distributed(self, group_name: str) -> None:
        from ray_tpu.collective import bootstrap_jax_distributed

        bootstrap_jax_distributed(self.world_size, self.rank, group_name)

    def bootstrap_torch_distributed(self, group_name: str) -> None:
        from ray_tpu.collective.rendezvous import bootstrap_torch_distributed

        bootstrap_torch_distributed(self.world_size, self.rank, group_name)

    def start(self, train_fn: Callable, config: Dict[str, Any],
              checkpoint: Optional[Checkpoint],
              dataset_shards: Optional[Dict[str, Any]]) -> None:
        ctx = TrainContext(self.rank, self.world_size,
                           experiment_name=self.experiment_name)
        self.session = TrainSession(ctx, checkpoint=checkpoint,
                                    dataset_shards=dataset_shards)
        session_mod.init_session(self.session)

        def run():
            try:
                if _takes_config(train_fn):
                    train_fn(config)
                else:
                    train_fn()
                self.session.finish()
            except BaseException as e:  # noqa: BLE001
                traceback.print_exc()
                self.session.finish(error=e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name=f"rt-train-rank{self.rank}")
        self._thread.start()

    def next_result(self) -> Dict[str, Any]:
        """Blocks until the worker reports, finishes, or errors."""
        item = self.session.results.get()
        if item["type"] == "error":
            err = item["error"]
            return {"type": "error", "message": repr(err),
                    "traceback": "".join(traceback.format_exception(
                        type(err), err, err.__traceback__))}
        return item

    def shutdown(self) -> None:
        session_mod.clear_session()


def _takes_config(fn: Callable) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return True


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, experiment_name: str):
        self.scaling = scaling
        self.experiment_name = experiment_name
        self.pg = None
        self.workers: List = []

    def start(self) -> None:
        n = self.scaling.num_workers
        if self.scaling.use_tpu and not self.scaling.resources_per_worker:
            # Multi-host TPU gang: the pod-slice PG shape (slice_group —
            # one bundle per host, chips pinned per bundle). A one-host
            # gang packs; a multi-host gang takes the ScalingConfig's
            # strategy (topology="v5p-N" already set STRICT_SPREAD).
            self.pg = slice_group(
                num_hosts=n,
                chips_per_host=self.scaling.tpu_chips_per_worker,
                cpus_per_host=self.scaling.cpus_per_worker,
                strategy=(self.scaling.placement_strategy if n > 1
                          else "PACK"),
                name=self.experiment_name)
        else:
            self.pg = placement_group(
                [self.scaling.bundle() for _ in range(n)],
                strategy=self.scaling.placement_strategy)
        if not self.pg.wait(timeout=300):
            remove_placement_group(self.pg)
            raise TimeoutError(
                f"could not reserve {n} x {self.scaling.bundle()} "
                f"(placement group timed out)")
        try:
            actor_cls = ray_tpu.remote(TrainWorker)
            bundle = self.scaling.bundle()
            self.workers = [
                actor_cls.options(
                    num_cpus=bundle.get("CPU", 1),
                    num_tpus=bundle.get("TPU", 0) or None,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, i),
                ).remote(i, n, self.experiment_name)
                for i in range(n)
            ]
            ray_tpu.get([w.ping.remote() for w in self.workers], timeout=300)
        except BaseException:
            # Don't leak the gang's reservation on a failed start.
            self.shutdown()
            raise

    def run_async(self, method: str, *args) -> List:
        return [getattr(w, method).remote(*args) for w in self.workers]

    def run(self, method: str, *args, timeout: Optional[float] = None) -> List:
        return ray_tpu.get(self.run_async(method, *args), timeout=timeout)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
        self.workers = []
        self.pg = None
