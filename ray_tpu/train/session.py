"""The per-worker training session.

Reference analog: ``_TrainSession`` (``train/_internal/session.py:132`` —
``report :612/:844``, ``get_checkpoint :902``, ``get_dataset_shard :1208``).
``report`` enqueues (metrics, checkpoint) into a bounded queue the driver
drains — backpressure keeps a fast training loop from outrunning a slow
driver, the same contract as the reference's result queue
(``trainable/function_trainable.py:199-264``).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 local_rank: int = 0, node_rank: int = 0,
                 experiment_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.experiment_name = experiment_name

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank


class TrainSession:
    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 queue_size: int = 2):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.results: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self.results.put({"type": "report", "metrics": dict(metrics),
                          "checkpoint": checkpoint})

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        self.finished.set()
        self.results.put({"type": "error", "error": error} if error
                         else {"type": "done"})


_session_lock = threading.Lock()
_session: Optional[TrainSession] = None


def init_session(session: TrainSession) -> None:
    global _session
    with _session_lock:
        _session = session


def clear_session() -> None:
    global _session
    with _session_lock:
        _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session: this API must be called inside a "
            "train_loop_per_worker launched by a Trainer")
    return _session


# ---- public per-worker API -------------------------------------------------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_context() -> TrainContext:
    return get_session().context


def get_dataset_shard(name: str = "train"):
    shard = get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(f"no dataset shard named {name!r}")
    return shard
