"""The per-worker training session.

Reference analog: ``_TrainSession`` (``train/_internal/session.py:132`` —
``report :612/:844``, ``get_checkpoint :902``, ``get_dataset_shard :1208``).
``report`` enqueues (metrics, checkpoint) into a bounded queue the driver
drains — backpressure keeps a fast training loop from outrunning a slow
driver, the same contract as the reference's result queue
(``trainable/function_trainable.py:199-264``).

Off-step-path reporting (ROADMAP item 2): the step loop's ``report`` call
only hands the metrics dict to a dedicated **drainer thread**; metric
coercion to host scalars (the device→host sync a live ``jax.Array`` leaf
forces) and the checkpoint completion fence happen on that thread, so the
fused-K launch loop never blocks behind a ``device_get`` or a slow
serialization. ``FastPathConfig.async_report=False`` restores the
synchronous path (the bench A/B's control leg).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FastPathConfig


def _to_host(value: Any) -> Any:
    """Coerce one metric leaf to a host value: device arrays become python
    scalars (size 1) or host ndarrays, everything else passes through.
    Duck-typed — works for jax.Array and np arrays without importing jax."""
    if hasattr(value, "__array__") and not isinstance(value, (str, bytes)):
        import numpy as np

        arr = np.asarray(value)  # the one device->host sync, on the drainer
        if arr.size == 1:
            return arr.reshape(()).item()
        return arr
    return value


def coerce_metrics(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Host-scalar coercion for a reported metrics dict (drainer-side)."""
    return {k: _to_host(v) for k, v in metrics.items()}


class TrainContext:
    def __init__(self, world_rank: int, world_size: int,
                 local_rank: int = 0, node_rank: int = 0,
                 experiment_name: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.experiment_name = experiment_name

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank


class TrainSession:
    def __init__(self, context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 queue_size: int = 2,
                 fast_path: Optional[FastPathConfig] = None):
        self.context = context
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.fast_path = fast_path or FastPathConfig()
        self.results: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # report handoff lane: the step loop appends, the drainer coerces/
        # fences and forwards into `results`. Bounded so a wedged driver
        # still backpressures eventually, but deep enough that a slow
        # checkpoint never stalls the loop mid-launch.
        self._handoff: "queue.Queue" = queue.Queue(maxsize=64)
        self._drainer: Optional[threading.Thread] = None
        self._drainer_lock = threading.Lock()

    # ---- the drainer thread -------------------------------------------------
    def _ensure_drainer(self) -> None:
        with self._drainer_lock:
            if self._drainer is None or not self._drainer.is_alive():
                self._drainer = threading.Thread(
                    target=self._drain_loop, daemon=True,
                    name=f"rt-train-report-drain-r{self.context.world_rank}")
                self._drainer.start()

    def _drain_loop(self) -> None:
        while True:
            item = self._handoff.get()
            if item is None:  # finish() sentinel follows the final put
                return
            try:
                if item["type"] == "report":
                    item["metrics"] = coerce_metrics(item["metrics"])
                    ckpt = item.get("checkpoint")
                    if ckpt is not None and hasattr(ckpt, "wait_pending"):
                        # the ack fence: an async save must complete before
                        # the report (and thus CheckpointManager) sees it
                        ckpt.wait_pending()
            except Exception as e:  # noqa: BLE001 — surfaced as an error
                item = {"type": "error", "error": e}  # round to the driver
                self.error = e
            self.results.put(item)
            if item["type"] != "report":
                return  # done/error terminates the drainer

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        """Hand one (metrics, checkpoint) round to the driver.

        Contract: the dict is shallow-copied at the call site (free — no
        device sync) and its leaves are coerced to host scalars on the
        session's drainer thread, so live ``jax.Array`` leaves are fine
        (the device→host sync happens off the step path) and the caller
        may reuse the dict object; the reported leaf VALUES must not be
        mutated in place. With ``async_report=False`` (FastPathConfig) coercion
        and the checkpoint fence run synchronously on the calling thread.
        Backpressure: the handoff lane is bounded (64 rounds) on the async
        path, the results queue (2 rounds) on the sync path.
        """
        if not self.fast_path.async_report:
            metrics = coerce_metrics(metrics)
            if checkpoint is not None and hasattr(checkpoint, "wait_pending"):
                checkpoint.wait_pending()
            self.results.put({"type": "report", "metrics": metrics,
                              "checkpoint": checkpoint})
            return
        self._ensure_drainer()
        # shallow copy: free (no device sync — the array leaves are shared,
        # not read), and a caller reusing one metrics dict across steps
        # keeps the old contract; leaf VALUES are still coerced lazily on
        # the drainer
        self._handoff.put({"type": "report", "metrics": dict(metrics),
                           "checkpoint": checkpoint})

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        self.finished.set()
        item = {"type": "error", "error": error} if error else {"type": "done"}
        if self.fast_path.async_report and self._drainer is not None \
                and self._drainer.is_alive():
            # ride the handoff lane so every earlier report drains first
            self._handoff.put(item)
            self._handoff.put(None)
        else:
            self.results.put(item)


_session_lock = threading.Lock()
_session: Optional[TrainSession] = None


def init_session(session: TrainSession) -> None:
    global _session
    with _session_lock:
        _session = session


def clear_session() -> None:
    global _session
    with _session_lock:
        _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session: this API must be called inside a "
            "train_loop_per_worker launched by a Trainer")
    return _session


# ---- public per-worker API -------------------------------------------------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_context() -> TrainContext:
    return get_session().context


def get_fast_path() -> FastPathConfig:
    """The trainer-configured fast-path knobs (steps_per_launch etc.) for
    this worker; a default config outside a session."""
    if _session is None:
        return FastPathConfig()
    return _session.fast_path


def get_dataset_shard(name: str = "train"):
    shard = get_session().dataset_shards.get(name)
    if shard is None:
        raise KeyError(f"no dataset shard named {name!r}")
    return shard
