"""Distributed training orchestration.

Reference analog: ``python/ray/train/`` — ``DataParallelTrainer`` +
``BackendExecutor`` + ``WorkerGroup`` + ``_TrainSession``
(SURVEY.md §2.3, §3.4). TPU-native redesign: the gradient path is never a
runtime service — each worker runs a jit-compiled step whose collectives are
XLA ops over the gang's mesh; the Train layer only places the gang (slice
placement group), bootstraps ``jax.distributed``, moves reported metrics and
checkpoints, and restarts the gang on failure.
"""

from ray_tpu.train.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    FastPathConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train.driver import StepDriver  # noqa: F401
from ray_tpu.train.session import (  # noqa: F401
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_fast_path,
    report,
)
from ray_tpu.train.trainer import (  # noqa: F401
    JaxTrainer,
    Result,
    TorchTrainer,
)
