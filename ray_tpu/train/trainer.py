"""JaxTrainer: the data-parallel trainer driving a gang of JAX workers.

Reference analog: ``DataParallelTrainer`` (``train/data_parallel_trainer.py:59``)
+ ``BackendExecutor`` (``_internal/backend_executor.py:46``): create the gang
in a placement group, bootstrap the collective backend, run the user loop on
every rank, drain reported (metrics, checkpoint) rounds, restart the gang
from the last checkpoint on failure (``FailureConfig.max_failures`` —
elastic-restart, like the reference). The torch/NCCL process-group bootstrap
(``train/torch/config.py:64``) is replaced by ``jax.distributed`` over the
GCS-KV rendezvous.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

DEFAULT_STORAGE = "/tmp/ray_tpu_results"


class TrainingFailedError(RuntimeError):
    pass


class Result:
    def __init__(self, metrics: Optional[Dict], checkpoint: Optional[Checkpoint],
                 path: str, error: Optional[str] = None,
                 metrics_history: Optional[List[Dict]] = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error
        self.metrics_history = metrics_history or []

    def __repr__(self):
        return (f"Result(metrics={self.metrics}, checkpoint={self.checkpoint}, "
                f"error={self.error})")


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 use_jax_distributed: bool = False,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.train_fn = train_loop_per_worker
        self.train_config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.use_jax_distributed = use_jax_distributed
        self.resume_checkpoint = resume_from_checkpoint

    @property
    def _dist_bootstrap(self):
        return ("bootstrap_jax_distributed" if self.use_jax_distributed
                else None)

    # -- dataset sharding -----------------------------------------------------
    def _shard_datasets(self, rank: int, world: int) -> Dict[str, Any]:
        shards = {}
        datasets = getattr(self, "_attempt_datasets", None) or self.datasets
        for name, ds in datasets.items():
            split = getattr(ds, "streaming_split", None)
            if split is not None:
                shards[name] = ds.streaming_split(world)[rank]
            elif isinstance(ds, (list, tuple)):
                shards[name] = list(ds[rank::world])
            else:
                shards[name] = ds  # caller shards by rank inside the loop
        return shards

    # -- the fit loop ---------------------------------------------------------
    def fit(self) -> Result:
        name = self.run_config.name or f"JaxTrainer_{uuid.uuid4().hex[:8]}"
        storage = self.run_config.storage_path or DEFAULT_STORAGE
        run_dir = os.path.join(storage, name)
        os.makedirs(run_dir, exist_ok=True)
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            run_dir, num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)

        failures_left = self.run_config.failure_config.max_failures
        latest_checkpoint = self.resume_checkpoint
        metrics_history: List[Dict] = []
        last_error: Optional[str] = None

        while True:
            # per-attempt dataset copies: a retry after worker death must
            # re-execute the dataset (fresh coordinator), and concurrent
            # trials sharing one Dataset object (Train-on-Tune, local
            # backend) must not see each other's split caches — Dataset's
            # __getstate__ scrubs the cache, so copy() isolates it
            import copy as _copy

            self._attempt_datasets = {
                name: (_copy.copy(ds)
                       if hasattr(ds, "reset_streaming_split") else ds)
                for name, ds in self.datasets.items()}
            group = WorkerGroup(self.scaling, name)
            group.start()
            try:
                if self._dist_bootstrap and self.scaling.num_workers > 1:
                    group.run(self._dist_bootstrap,
                              f"{name}:{uuid.uuid4().hex[:6]}", timeout=300)
                n = self.scaling.num_workers
                ray_tpu.get([
                    w.start.remote(self.train_fn, self.train_config,
                                   latest_checkpoint,
                                   self._shard_datasets(i, n),
                                   self.run_config.fast_path)
                    for i, w in enumerate(group.workers)], timeout=300)
                error = self._drain_results(group, manager, metrics_history)
                if error is None:
                    final = metrics_history[-1] if metrics_history else None
                    return Result(final, manager.best_checkpoint
                                  or manager.latest_checkpoint,
                                  run_dir, None, metrics_history)
                last_error = error
                if failures_left == 0:
                    raise TrainingFailedError(
                        f"training failed (no restart budget left): {error}")
                failures_left -= 1
                self._emit_gang_restart(
                    name, error,
                    self.run_config.failure_config.max_failures
                    - failures_left)
                latest_checkpoint = manager.latest_checkpoint or latest_checkpoint
            finally:
                group.shutdown()

    @staticmethod
    def _emit_gang_restart(name: str, error: str, restart_num: int) -> None:
        """Stamp a FailureConfig-driven gang restart into the failure plane
        (PR 5): a FailureEvent on the feed (visible in `rt errors` /
        `rt doctor`) plus a `rt_actor_restarts_total` tick, so train-level
        recovery is observable like every other restart. Best-effort —
        recovery must not fail on telemetry."""
        try:
            from ray_tpu.core import failure as F
            from ray_tpu.core.worker import global_worker

            backend = global_worker().backend
            if backend is not None and hasattr(backend, "_gcs"):
                err = ((error or "").strip().splitlines() or [""])[0][:300]
                category = (F.WORKER_CRASH if "died" in err
                            else F.TASK_ERROR)
                F.emit(backend.io.spawn, backend._gcs, category,
                       f"JaxTrainer gang restart {restart_num} "
                       f"(from last checkpoint): {err}",
                       name="JaxTrainer", experiment=name,
                       restarting=True, gang_restart=True)
            from ray_tpu.util import metrics as M

            M.get_or_create(
                M.Counter, "rt_actor_restarts_total",
                "Actor restarts scheduled by the GCS after a failure").inc()
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def _drain_results(self, group: WorkerGroup, manager: CheckpointManager,
                       history: List[Dict]) -> Optional[str]:
        """Drain symmetric report rounds; returns error string on failure."""
        active = list(group.workers)
        while active:
            try:
                round_results = ray_tpu.get(
                    [w.next_result.remote() for w in active])
            except Exception as e:  # actor died (worker process crash)
                return f"worker died: {e!r}"
            errors = [r for r in round_results if r["type"] == "error"]
            if errors:
                return errors[0].get("message", "unknown") + "\n" + \
                    errors[0].get("traceback", "")
            reports = [(w, r) for w, r in zip(active, round_results)
                       if r["type"] == "report"]
            if reports:
                rank0_report = reports[0][1]
                metrics = dict(rank0_report["metrics"])
                ckpt = rank0_report.get("checkpoint")
                if ckpt is not None:
                    saved = manager.register(ckpt, metrics)
                    metrics["checkpoint_path"] = saved.path
                metrics["_round"] = len(history)
                metrics["_timestamp"] = time.time()
                history.append(metrics)
            active = [w for w, r in zip(active, round_results)
                      if r["type"] == "report"]
        return None


class TorchTrainer(JaxTrainer):
    """Data-parallel torch training (reference: ``train/torch/TorchTrainer``).

    Same gang/report/checkpoint machinery as JaxTrainer; the collective
    backend is a torch.distributed gloo process group bootstrapped through
    the GCS-KV rendezvous (CPU torch — this framework's compute path is
    JAX/TPU, but torch users keep their Train API). The user loop calls
    ``torch.distributed`` collectives / wraps modules in DDP as usual.
    """

    def __init__(self, *args, use_torch_distributed: bool = True, **kwargs):
        kwargs.pop("use_jax_distributed", None)
        super().__init__(*args, **kwargs)
        self.use_torch_distributed = use_torch_distributed

    @property
    def _dist_bootstrap(self):
        return ("bootstrap_torch_distributed" if self.use_torch_distributed
                else None)
