"""Checkpoints: directory-backed, with orbax pytree helpers.

Reference analog: ``ray.train.Checkpoint`` (``train/_checkpoint.py``) — a
handle to a directory — plus ``CheckpointManager``
(``_internal/checkpoint_manager.py``, top-k retention). TPU-native: pytree
state saves through orbax (async-capable, works with sharded jax.Array);
plain files work too.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Small state dicts — serialized as a single file."""
        import cloudpickle

        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        with open(os.path.join(d, "_dict_checkpoint.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle

        with open(os.path.join(self.path, "_dict_checkpoint.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def as_directory(self) -> str:
        return self.path

    # ---- pytree state (orbax) ----------------------------------------------
    def save_pytree(self, tree: Any, name: str = "state") -> None:
        import orbax.checkpoint as ocp

        path = os.path.join(self.path, name)
        shutil.rmtree(path, ignore_errors=True)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, tree)

    def load_pytree(self, name: str = "state", abstract_tree: Any = None) -> Any:
        import orbax.checkpoint as ocp

        path = os.path.join(self.path, name)
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(path, abstract_tree) if abstract_tree is not None \
                else ckptr.restore(path)

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-k retention by score (reference: ``_internal/checkpoint_manager.py``)."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: List[Dict] = []
        self._counter = 0
        os.makedirs(run_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Move the checkpoint under the run dir and apply retention."""
        dest = os.path.join(self.run_dir, f"checkpoint_{self._counter:06d}")
        self._counter += 1
        if checkpoint.path != dest:
            shutil.move(checkpoint.path, dest)
        entry = {"path": dest, "metrics": dict(metrics)}
        self._entries.append(entry)
        with open(os.path.join(dest, "_metrics.json"), "w") as f:
            json.dump(entry["metrics"], f, default=str)
        self._apply_retention()
        return Checkpoint(dest)

    def _score(self, entry: Dict) -> float:
        v = entry["metrics"].get(self.score_attribute, 0.0)
        try:
            v = float(v)
        except (TypeError, ValueError):
            v = 0.0
        return v if self.score_order == "max" else -v

    def _apply_retention(self) -> None:
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        if self.score_attribute:
            ranked = sorted(self._entries, key=self._score, reverse=True)
        else:
            ranked = list(reversed(self._entries))  # keep most recent
        for entry in ranked[self.num_to_keep:]:
            shutil.rmtree(entry["path"], ignore_errors=True)
            self._entries.remove(entry)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        if self.score_attribute:
            entry = max(self._entries, key=self._score)
        else:
            entry = self._entries[-1]
        return Checkpoint(entry["path"])

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return Checkpoint(self._entries[-1]["path"]) if self._entries else None
