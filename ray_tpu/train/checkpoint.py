"""Checkpoints: directory-backed, with orbax pytree helpers.

Reference analog: ``ray.train.Checkpoint`` (``train/_checkpoint.py``) — a
handle to a directory — plus ``CheckpointManager``
(``_internal/checkpoint_manager.py``, top-k retention). TPU-native: pytree
state saves through orbax (async-capable, works with sharded jax.Array);
plain files work too.

Async saves (``save_pytree(..., blocking=False)``) run on a writer thread
so the step loop never waits on serialization; the **completion fence**
(``wait_pending``) runs at ack boundaries only — a checkpoint is fenced
before it crosses a process boundary (``__reduce__``) and before
``CheckpointManager.register`` admits it, so a gang restart can never
resume from a half-written directory.
"""

from __future__ import annotations

import heapq
import json
import os
import shutil
import tempfile
import threading
from typing import Any, Dict, List, Optional

import cloudpickle


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._pending_lock = threading.Lock()
        # rt: guarded-by(_pending_lock) — in-flight async save threads
        self._pending: List[threading.Thread] = []
        self._pending_errors: List[BaseException] = []

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Small state dicts — serialized as a single file."""
        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        with open(os.path.join(d, "_dict_checkpoint.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "_dict_checkpoint.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def as_directory(self) -> str:
        return self.path

    # ---- pytree state (orbax) ----------------------------------------------
    def _save_pytree_sync(self, tree: Any, name: str) -> None:
        import orbax.checkpoint as ocp

        path = os.path.join(self.path, name)
        shutil.rmtree(path, ignore_errors=True)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, tree)

    def save_pytree(self, tree: Any, name: str = "state", *,
                    blocking: Optional[bool] = None) -> None:
        """Save a pytree under this checkpoint directory.

        ``blocking=False`` hands the serialization to a writer thread and
        returns immediately — the off-step-path product configuration. The
        save is only guaranteed durable after :meth:`wait_pending` (called
        automatically when the checkpoint is pickled across a process
        boundary, and by ``CheckpointManager.register``). Default
        (``blocking=None``): inside a train session the trainer's
        ``FastPathConfig.async_checkpoint`` decides; standalone saves
        block (durable on return, the pre-fast-path contract).
        """
        if blocking is None:
            from ray_tpu.train import session as _session_mod

            live = _session_mod._session  # None outside a train loop
            blocking = (True if live is None
                        else not live.fast_path.async_checkpoint)
        if blocking:
            self._save_pytree_sync(tree, name)
            return
        # Donation safety: the step loop donates (params, opt_state) into
        # the NEXT launch, which would delete the buffers this writer is
        # about to serialize. Snapshot device arrays with an on-device copy
        # (async dispatch — no host sync on the calling thread).
        try:
            import jax
            import jax.numpy as jnp

            tree = jax.tree.map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                tree)
        except ImportError:  # host-only trees save as-is
            pass

        def writer():
            try:
                self._save_pytree_sync(tree, name)
            except BaseException as e:  # noqa: BLE001 — re-raised at fence
                with self._pending_lock:
                    self._pending_errors.append(e)

        t = threading.Thread(target=writer, daemon=True,
                             name="rt-ckpt-writer")
        with self._pending_lock:
            self._pending.append(t)
        t.start()

    def wait_pending(self, timeout: Optional[float] = None) -> None:
        """The completion fence: block until every async save of this
        checkpoint is durable; re-raise the first writer failure. Idempotent
        and cheap when nothing is pending."""
        with self._pending_lock:
            pending = list(self._pending)
        for t in pending:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"checkpoint save still running after {timeout}s "
                    f"({self.path})")
        with self._pending_lock:
            self._pending = [t for t in self._pending if t.is_alive()]
            if self._pending_errors:
                err = self._pending_errors[0]
                self._pending_errors = []
                raise err

    def load_pytree(self, name: str = "state", abstract_tree: Any = None) -> Any:
        import orbax.checkpoint as ocp

        self.wait_pending()
        path = os.path.join(self.path, name)
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(path, abstract_tree) if abstract_tree is not None \
                else ckptr.restore(path)

    def __reduce__(self):
        # pickling IS an ack boundary: the receiving process (driver,
        # another worker) must never observe a half-written directory
        self.wait_pending()
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-k retention by score (reference: ``_internal/checkpoint_manager.py``).

    Each entry's score is computed ONCE at ``register`` and kept on a heap
    keyed (score, age): eviction pops the worst entry directly instead of
    re-scoring and re-sorting the full retention list per call.
    """

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: List[Dict] = []
        self._heap: List = []  # (rank_key, seq, entry) — min = evict first
        self._counter = 0
        os.makedirs(run_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Move the checkpoint under the run dir and apply retention.

        Fences any in-flight async save first: an unfinished checkpoint is
        never acked into the manager (the gang-restart recovery source).
        """
        checkpoint.wait_pending()
        dest = os.path.join(self.run_dir, f"checkpoint_{self._counter:06d}")
        seq = self._counter
        self._counter += 1
        if checkpoint.path != dest:
            shutil.move(checkpoint.path, dest)
        entry = {"path": dest, "metrics": dict(metrics),
                 "score": self._score_value(metrics), "seq": seq}
        self._entries.append(entry)
        # rank_key: keep-most-recent mode ranks purely by age (seq breaks
        # the tie anyway); score mode ranks by the once-computed score
        rank = entry["score"] if self.score_attribute else 0.0
        heapq.heappush(self._heap, (rank, seq, entry))
        with open(os.path.join(dest, "_metrics.json"), "w") as f:
            json.dump(entry["metrics"], f, default=str)
        self._apply_retention()
        return Checkpoint(dest)

    def _score_value(self, metrics: Dict[str, Any]) -> float:
        v = metrics.get(self.score_attribute, 0.0) \
            if self.score_attribute else 0.0
        try:
            v = float(v)
        except (TypeError, ValueError):
            v = 0.0
        return v if self.score_order == "max" else -v

    def _apply_retention(self) -> None:
        if self.num_to_keep is None:
            return
        while len(self._entries) > self.num_to_keep and self._heap:
            # entries leave _entries only here, right after their pop, so
            # a popped entry is always live
            _, _, entry = heapq.heappop(self._heap)
            shutil.rmtree(entry["path"], ignore_errors=True)
            self._entries.remove(entry)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        if self.score_attribute:
            entry = max(self._entries, key=lambda e: e["score"])
        else:
            entry = self._entries[-1]
        return Checkpoint(entry["path"])

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return Checkpoint(self._entries[-1]["path"]) if self._entries else None
