"""Train-layer configuration objects.

Reference analog: ``ray.air.config`` (``ScalingConfig air/config.py:94``,
``FailureConfig :523``, ``CheckpointConfig :574``, ``RunConfig :723``).
ScalingConfig speaks TPU natively: workers × chips-per-worker, with an
optional topology string ("v5p-16") that implies the gang shape.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional

from ray_tpu.core.resources import CPU, TPU


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    tpu_chips_per_worker: int = 0
    cpus_per_worker: float = 1
    resources_per_worker: Optional[Dict[str, float]] = None
    topology: Optional[str] = None      # e.g. "v5p-16": 16 chips, 4/host
    placement_strategy: str = "PACK"    # SPREAD across hosts for slices

    def __post_init__(self):
        if self.topology:
            m = re.match(r"v\d+[a-z]*-(\d+)$", self.topology)
            if not m:
                raise ValueError(
                    f"topology {self.topology!r} not understood; expected "
                    f"like 'v5p-16'")
            total_chips = int(m.group(1))
            from ray_tpu._private.config import get_config

            per_host = get_config().tpu_chips_per_host
            self.num_workers = max(1, total_chips // per_host)
            self.tpu_chips_per_worker = min(total_chips, per_host)
            self.placement_strategy = "STRICT_SPREAD" if self.num_workers > 1 else "PACK"

    def bundle(self) -> Dict[str, float]:
        b: Dict[str, float] = {CPU: self.cpus_per_worker}
        if self.tpu_chips_per_worker:
            b[TPU] = float(self.tpu_chips_per_worker)
        b.update(self.resources_per_worker or {})
        return b

    @property
    def use_tpu(self) -> bool:
        return self.tpu_chips_per_worker > 0


@dataclasses.dataclass
class FastPathConfig:
    """The fused-K training fast path (ROADMAP item 2).

    ``steps_per_launch`` is the launch-amortization knob: the
    :class:`~ray_tpu.train.driver.StepDriver` stacks K data-plane batches
    and runs ONE compiled launch per K optimizer steps
    (``parallel/train_step.make_multi_step``'s ``lax.scan``), degrading to
    single-step for the 1f1b pipeline schedule and for ragged tail
    batches. ``async_report`` / ``async_checkpoint`` keep ``session.report``
    metric coercion and checkpoint serialization on the session's drainer
    thread instead of the step loop; ``prefetch_batches`` bounds the data
    plane's lookahead (host pull + device put ahead of the consuming step).
    """

    steps_per_launch: int = 1
    prefetch_batches: int = 2
    async_report: bool = True
    async_checkpoint: bool = True

    def __post_init__(self):
        if self.steps_per_launch < 1:
            raise ValueError(
                f"steps_per_launch must be >= 1, got {self.steps_per_launch}")


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # gang restarts from last checkpoint


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # default: /tmp/ray_tpu_results
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    fast_path: FastPathConfig = dataclasses.field(default_factory=FastPathConfig)
    # Tune stop criteria: {"training_iteration": N, "<metric>": value} or
    # callable(trial_id, result) -> bool (reference: air.RunConfig.stop)
    stop: Optional[Any] = None
