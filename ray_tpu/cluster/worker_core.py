"""ClusterBackend: the per-process core-worker library.

Reference analog: ``src/ray/core_worker/`` embedded in every driver/worker —
task submission (``CoreWorkerDirectTaskSubmitter``), direct actor calls with
per-caller ordering (``CoreWorkerDirectActorTaskSubmitter`` +
``SequentialActorSubmitQueue``), the in-process memory store for small
objects, and plasma access for large ones. One instance lives in the driver
and one in every worker process; task-executing code sees the same
``ray_tpu.*`` API through it.

Object resolution order on ``get`` (mirrors the reference's
memory-store → plasma → owner/directory path, SURVEY.md §3.2):
  1. local memory store (we own it, or cached),
  2. local shm store (zero-copy),
  3. owner's memory store over RPC (ref carries the owner address),
  4. location directory → raylet pull → local shm.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu import _native
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.serialization import SerializationContext, unpack_payload
from ray_tpu.core.actor import ActorHandle
from ray_tpu.core.backend import RuntimeBackend
from ray_tpu.core import object_ledger
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import (
    NodeLabelStrategy,
    resources_from_options,
    validate_options,
)
from ray_tpu.core.worker import global_worker
from ray_tpu.cluster import stream as rt_stream
from ray_tpu.cluster.object_store import PlasmaStore
from ray_tpu.runtime_env import prepare_runtime_env
from ray_tpu.util import chaos as _chaos
from ray_tpu.util import metrics as M
from ray_tpu.util import tracing
from ray_tpu.util.placement_group import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.util.tqdm_rt import maybe_render
from ray_tpu.cluster.rpc import (
    ChannelBroken,
    ConnectionLost,
    ConnectionPool,
    EventLoopThread,
    RpcClient,
    RpcServer,
    spawn_task,
)
from ray_tpu.core import failure as F
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnschedulableError,
    BackpressureError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    OwnerDiedError,
    SchedulingTimeoutError,
    TaskError,
    WorkerCrashedError,
)

logger = logging.getLogger("ray_tpu.worker_core")

_SMALL = lambda: get_config().max_direct_call_object_size


def _trace_ctx():
    """Child-span wire context when tracing is on or a span is ambient
    (None otherwise)."""
    return tracing.context_for_submit()


_phase_hist = None


def _observe_phases(phases: Dict[str, float]) -> None:
    """rt_task_phase_seconds{phase=...}: the Prometheus twin of the span's
    phase table, observed in the owner process (whose metrics pusher is
    live). Reached only for traced tasks — never on the untraced path."""
    global _phase_hist
    try:
        if _phase_hist is None:
            _phase_hist = M.get_or_create(
                M.Histogram, "rt_task_phase_seconds",
                "Per-phase task latency breakdown (traced tasks)",
                tag_keys=("phase",))
        for name, secs in phases.items():
            _phase_hist.observe(secs, {"phase": name})
    except Exception:  # noqa: BLE001 — observability never fails the task
        pass


# Recovery telemetry (failure plane): owner-side retry / lineage-
# reconstruction counters + the reconstruction-latency histogram. All
# lazily registered so the untraced happy path never touches the registry.
_recovery_metrics: Optional[Dict[str, Any]] = None

_RECONSTRUCT_BUCKETS = (0.005, 0.02, 0.1, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0,
                        300.0)


def _observe_retry() -> None:
    try:
        _get_recovery_metrics()["retries"].inc()
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def _observe_reconstruction(outcome: str, seconds: float) -> None:
    try:
        m = _get_recovery_metrics()
        m["reconstructions"].inc(1.0, {"outcome": outcome})
        m["reconstruct_hist"].observe(seconds)
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def _get_recovery_metrics() -> Dict[str, Any]:
    global _recovery_metrics
    if _recovery_metrics is None:
        _recovery_metrics = {
            "retries": M.get_or_create(
                M.Counter, "rt_task_retries_total",
                "Owner-side task resubmissions after a retriable failure"),
            "reconstructions": M.get_or_create(
                M.Counter, "rt_object_reconstructions_total",
                "Lineage reconstructions of lost objects by outcome",
                tag_keys=("outcome",)),
            "reconstruct_hist": M.get_or_create(
                M.Histogram, "rt_object_reconstruction_seconds",
                "Wall time of one lineage reconstruction "
                "(resubmit to reply)",
                boundaries=_RECONSTRUCT_BUCKETS),
        }
    return _recovery_metrics




class _MemoryStore:
    """Owner-side store of serialized payloads with async readiness events."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._data: Dict[str, bytes] = {}
        self._events: Dict[str, asyncio.Event] = {}
        self._lock = threading.Lock()

    def register_pending(self, oid_hex: str) -> None:
        with self._lock:
            if oid_hex not in self._events and oid_hex not in self._data:
                self._events[oid_hex] = asyncio.Event()

    def put(self, oid_hex: str, payload: bytes) -> None:
        with self._lock:
            self._data[oid_hex] = payload
            ev = self._events.pop(oid_hex, None)
        if ev is not None:
            self._loop.call_soon_threadsafe(ev.set)

    def mark_external(self, oid_hex: str) -> None:
        """The value went to plasma; wake waiters with no inline payload."""
        with self._lock:
            ev = self._events.pop(oid_hex, None)
        if ev is not None:
            self._loop.call_soon_threadsafe(ev.set)

    def get(self, oid_hex: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(oid_hex)

    def is_pending(self, oid_hex: str) -> bool:
        with self._lock:
            return oid_hex in self._events

    async def wait_ready(self, oid_hex: str, timeout: Optional[float]) -> bool:
        with self._lock:
            if oid_hex in self._data:
                return True
            ev = self._events.get(oid_hex)
        if ev is None:
            return True
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def delete(self, oid_hex: str) -> None:
        with self._lock:
            self._data.pop(oid_hex, None)


class _StreamState:
    """Owner-side state of one streaming-generator task (reference:
    ``ObjectRefStream``, ``core_worker/task_manager.h:96``)."""

    def __init__(self, task_id_hex: str, owner_address: str,
                 max_buffer: int, loop: asyncio.AbstractEventLoop):
        self.task_id_hex = task_id_hex
        self.owner_address = owner_address
        self.max_buffer = max_buffer
        self.produced = 0
        self.consumed = 0
        self.done = False
        self.closed = False                    # consumer abandoned the stream
        self.error_payload: Optional[bytes] = None
        self._event = asyncio.Event()          # new item / done (loop-affine)
        self._space = asyncio.Event()          # consumer caught up
        self._space.set()
        self.loop = loop

    def notify(self) -> None:
        self._event.set()
        if (self.done or self.closed
                or self.produced - self.consumed <= self.max_buffer):
            self._space.set()  # done/closed also frees a blocked producer ack
        else:
            self._space.clear()


class ObjectRefGenerator:
    """Iterator of ObjectRefs for ``num_returns="streaming"`` tasks
    (reference: ``StreamingObjectRefGenerator``, ``_raylet.pyx:267``).
    Yields per-item refs in production order; iteration ends when the
    generator task completes. Consuming an item releases backpressure."""

    def __init__(self, backend: "ClusterBackend", state: _StreamState):
        self._backend = backend
        self._state = state

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        st = self._state

        async def _wait_next():
            while True:
                if st.consumed < st.produced:
                    idx = st.consumed
                    st.consumed += 1
                    st.notify()
                    return idx
                if st.done or st.closed:
                    return None
                st._event.clear()
                await st._event.wait()

        idx = self._backend.io.run(_wait_next())
        if idx is None:
            raise StopIteration
        task_id = TaskID.from_hex(st.task_id_hex)
        return ObjectRef(ObjectID.for_return(task_id, idx),
                         owner=st.owner_address)

    def completed(self) -> bool:
        return self._state.done and self._state.consumed >= self._state.produced

    def close(self) -> None:
        """Abandon the stream: releases the producer's backpressure ack so
        the executor worker stops instead of blocking forever, and drops the
        owner-side stream state. Called automatically on GC."""
        st = self._state
        if st.closed:
            return
        st.closed = True
        self._backend._streams.pop(st.task_id_hex, None)
        self._backend.loop.call_soon_threadsafe(st.notify)

    def __del__(self):
        try:
            if not self.completed():
                self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class _ActorConn:
    """Ordered submission pipe to one actor (per-caller FIFO)."""

    def __init__(self, actor_id_hex: str):
        self.actor_id_hex = actor_id_hex
        self.address: Optional[str] = None
        self.send_lock: Optional[asyncio.Lock] = None
        self.dead_reason: Optional[str] = None
        self.dead_cause: Optional[Dict] = None  # failure.py wire dict
        self.max_task_retries: int = 0


class ClusterBackend(RuntimeBackend):
    def __init__(self, *, gcs_address: str, raylet_address: str, node_id: str,
                 session_name: str, job_id: JobID, role: str = "driver",
                 namespace: Optional[str] = None,
                 loop_thread: Optional[EventLoopThread] = None,
                 shared_store: bool = True):
        self.role = role
        # False = Ray-Client mode (reference: ray.client / util/client):
        # this process does NOT share the node's /dev/shm, so large objects
        # travel via the raylet's chunked put/get RPCs instead of mmap.
        self.shared_store = shared_store
        self.job_id = job_id
        self.namespace = namespace or "default"
        self.node_id = node_id
        self.session_name = session_name
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.serde = SerializationContext()
        self.io = loop_thread or EventLoopThread(name=f"rt-{role}-io")
        self.loop = self.io.loop
        self.plasma = PlasmaStore(session_name, create_dir=True)
        self.memory_store = _MemoryStore(self.loop)
        self.server = RpcServer(self.loop)
        self.server.register("get_object", self._rpc_get_object)
        self.server.register("stream_item", self._rpc_stream_item)
        # push-stream subscription (cluster/stream.py): a consumer binds a
        # one-way push channel on its existing connection to this process
        self.server.register("stream_subscribe", self._rpc_stream_subscribe)
        # streaming-generator push handshake: the EXECUTING worker
        # announces its stream source; this owner subscribes and drains
        # items over one-way frames instead of one acked RPC per item
        self.server.register("stream_begin", self._rpc_stream_begin)
        # task_id_hex -> _StreamState for in-flight streaming generators
        self._streams: Dict[str, _StreamState] = {}
        self._pool = ConnectionPool(peer_id=f"{role}:{job_id.hex()}")
        self._gcs: Optional[RpcClient] = None
        self._raylet: Optional[RpcClient] = None
        self._exported_fns: set = set()
        self._fn_cache: Dict[str, Any] = {}
        self._actor_conns: Dict[str, _ActorConn] = {}
        self._shutdown = False
        self._cluster_shutdown_hook = None
        self._current_task_id: Optional[str] = None  # set by worker_main
        self._blocked_notified: set = set()
        self._pg_addr_cache: Dict[Tuple[str, int], str] = {}
        # Lineage for owner-side reconstruction (reference:
        # ``object_recovery_manager.h:41-94`` — when every copy of a task's
        # return object is lost, the OWNER resubmits the creating task).
        # oid_hex -> submit payload; kept only for returns that went to
        # plasma (small returns live in this process's memory store).
        self._lineage: Dict[str, Dict] = {}
        self._reconstructing: Dict[str, asyncio.Future] = {}
        # Tombstones for explicitly freed objects we own: lets a borrower's
        # get fail fast instead of waiting out the directory timeout.
        self._freed: Dict[str, None] = {}
        # client-side failure-emission rate limit (see _failure_event)
        self._failure_limiter = F.EmitLimiter()
        # runtime_env json -> prepared wire form (working_dir uploaded once)
        self._prepared_envs: Dict[str, Optional[Dict]] = {}

    # ---- bootstrap ----------------------------------------------------------
    def connect(self) -> None:
        # chaos plane: a process spawned into a tortured cluster arms
        # before any RPC it issues can be a target (worker processes get
        # the plan injected by their raylet at spawn; a driver attaching to
        # a chaos run sets the same env explicitly)
        plan_json = os.environ.get("RT_CHAOS_PLAN_JSON")
        armed_from_env = False
        if plan_json:
            try:
                _chaos.arm(plan_json)
                armed_from_env = True
            except (ValueError, TypeError):
                logger.warning("RT_CHAOS_PLAN_JSON did not parse as a "
                               "ChaosPlan; ignoring")

        async def _go():
            await self.server.start()
            self._gcs = RpcClient(self.gcs_address, peer_id=self.role,
                                  auto_reconnect=True)
            try:
                await self._gcs.connect()
            except (OSError, ConnectionLost):
                # ConnectionLost too: connect() ends with a hello RPC that
                # can die mid-handshake when the head is going down
                if self.role != "worker":
                    raise
                # Degraded boot: the GCS is unreachable (outage/failover)
                # but a worker only needs its RAYLET to serve pushes — boot
                # anyway and let the auto-reconnect client re-dial at first
                # use, so a raylet running degraded can still grow its pool
                # instead of crash-looping spawns against a dead head.
                self._gcs._closed = True
            self._raylet = RpcClient(self.raylet_address, peer_id=self.role)
            await self._raylet.connect()

        self.io.run(_go(), timeout=get_config().gcs_rpc_timeout_s)
        if armed_from_env and self.role in ("driver", "client"):
            # drivers have no raylet maintenance loop; without this their
            # buffered rpc.* injection events would only drain when the
            # log-forward loop happens to run (and never with
            # log_to_driver off)
            self.io.spawn(self._chaos_drain_loop())
        if self.role in ("driver", "client") and get_config().log_to_driver:
            self.io.spawn(self._log_forward_loop())
        if object_ledger.enabled():
            # ledger snapshots ride the KV like metrics do, so `rt memory`
            # can join owner/call-site info from every process
            object_ledger.get_ledger().ensure_pusher()

    async def _log_forward_loop(self) -> None:
        """Echo worker stdout/stderr lines to this driver's stderr with a
        worker prefix (reference: ``_private/log_monitor.py`` +
        ``worker.print_logs``). EVERY node's raylet is polled — one
        long-poll task per raylet, refreshed from the GCS node table — so a
        multi-host cluster's remote prints reach the driver too. Each
        poller starts at the raylet's CURRENT seq (no history replay)."""
        polled: Dict[str, asyncio.Task] = {}
        while not self._shutdown:
            try:
                nodes = await self._gcs.call("list_nodes", {})
            except Exception:  # noqa: BLE001 — teardown
                return
            for n in nodes:
                addr = n.get("address")
                if not addr or not n.get("alive"):
                    continue
                t = polled.get(addr)
                if t is None or t.done():
                    polled[addr] = spawn_task(self._poll_node_logs(addr))
            self._drain_chaos_events()
            await asyncio.sleep(10.0)

    async def _chaos_drain_loop(self) -> None:
        while not self._shutdown:
            self._drain_chaos_events()
            await asyncio.sleep(2.0)

    def _drain_chaos_events(self) -> None:
        """Ship buffered rpc.* injection events so they reach
        `rt errors --origin chaos` (called from _chaos_drain_loop for
        env-armed drivers, and opportunistically from the log-poll tick)."""
        for ev in _chaos.drain_events():
            F.emit_raw(spawn_task, self._gcs, ev)

    async def _poll_node_logs(self, address: str) -> None:
        try:
            client = await self._pool.get(address)
            head = await client.call("poll_logs", {"after": None},
                                     timeout=15.0)
            seq = head.get("seq", 0)
        except Exception:  # noqa: BLE001 — raylet without log pump
            return
        while not self._shutdown:
            try:
                reply = await client.call(
                    "poll_logs", {"after": seq, "timeout": 5.0,
                                  "job_id": self.job_id.hex()},
                    timeout=30.0)
            except Exception:  # noqa: BLE001 — node gone; outer loop retries
                return
            for e in reply.get("entries", ()):
                line = e["line"]
                # progress-bar magic lines render compactly instead of
                # spamming raw JSON (util/tqdm_rt.py)
                bar = maybe_render(line)
                if bar is not None:
                    line = bar
                print(f"\x1b[36m(worker {e['worker_id'][:8]})\x1b[0m "
                      f"{line}", file=sys.stderr)
            seq = reply.get("seq", seq)

    @property
    def address(self) -> str:
        return self.server.address

    # ---- serialization helpers ---------------------------------------------
    def _serialize_arg(self, value: Any) -> Tuple:
        if isinstance(value, ObjectRef):
            if object_ledger.enabled():
                object_ledger.get_ledger().record_task_arg(value.hex())
            return ("ref", value._descriptor())
        payload = self.serde.serialize(value).to_bytes()
        if len(payload) > _SMALL():
            ref = self._put_payload_plasma(payload)
            return ("ref", ref._descriptor())
        return ("val", payload)

    def _put_payload_plasma(self, payload: bytes,
                            oid: Optional[ObjectID] = None) -> ObjectRef:
        oid = oid or global_worker().next_put_id()
        if not self.shared_store:
            self.io.run(self._upload_object(oid.hex(), payload))
            if object_ledger.enabled():
                object_ledger.get_ledger().record_put(
                    oid.hex(), len(payload), "plasma", owner=self.address)
            return ObjectRef(oid, owner=self.address)
        self.plasma.write_whole(oid, payload)
        self.io.run(self._raylet.call("seal_object",
                                      {"oid": oid.hex(), "size": len(payload)}))
        if object_ledger.enabled():
            object_ledger.get_ledger().record_put(
                oid.hex(), len(payload), "plasma", owner=self.address)
        return ObjectRef(oid, owner=self.address)

    async def _upload_object(self, oid_hex: str, payload: bytes) -> None:
        """Client mode: chunked upload into the attached raylet's store."""
        chunk = get_config().object_transfer_chunk_bytes
        total = len(payload)
        off = 0
        while True:
            end = min(off + chunk, total)
            reply = await self._raylet.call("put_object_chunk", {
                "oid": oid_hex, "offset": off, "total": total,
                "data": payload[off:end], "seal": end >= total})
            if reply.get("error"):
                raise RuntimeError(f"client put failed: {reply['error']}")
            if reply.get("dup"):
                return  # already in the store — done, don't keep streaming
            off = end
            if off >= total:
                return

    async def _download_object(self, oid_hex: str,
                               timeout) -> Optional[memoryview]:
        """Client mode: chunked download from the attached raylet (which
        serves shm and spill copies alike)."""
        def _checked(reply) -> Optional[bytes]:
            data = reply.get("data")
            if data is None:
                return None
            crc = reply.get("crc")
            if crc is not None:
                ours = _native.checksum(data, reply.get("crc_kind", "crc32c"))
                if ours is not None and ours != crc:
                    raise ConnectionError(
                        f"chunk checksum mismatch downloading {oid_hex}")
            return data

        chunk = get_config().object_transfer_chunk_bytes
        first = await self._raylet.call(
            "get_object_chunk", {"oid": oid_hex, "offset": 0, "size": chunk},
            timeout=timeout)
        data = _checked(first)
        if data is None:
            return None
        buf = bytearray(first["total"])
        buf[:len(data)] = data
        off = len(data)
        while off < len(buf):
            r = await self._raylet.call(
                "get_object_chunk",
                {"oid": oid_hex, "offset": off, "size": chunk},
                timeout=timeout)
            data = _checked(r)
            if not data:
                return None
            buf[off:off + len(data)] = data
            off += len(data)
        return memoryview(bytes(buf))

    # ---- objects ------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        payload = self.serde.serialize(value).to_bytes()
        oid = global_worker().next_put_id()
        if len(payload) > _SMALL():
            return self._put_payload_plasma(payload, oid)
        self.memory_store.put(oid.hex(), payload)
        if object_ledger.enabled():
            object_ledger.get_ledger().record_put(
                oid.hex(), len(payload), "memory", owner=self.address)
        return ObjectRef(oid, owner=self.address)

    async def _resolve_payload(self, ref: ObjectRef, timeout: Optional[float],
                               pin_held: bool = False) -> memoryview:
        """The 4-step resolution; returns the serialized payload.

        ``pin_held``: the caller already holds a raylet pin covering this oid
        (batched ``get``), so the per-oid pin around the fetch is skipped.
        """
        oid_hex = ref.hex()
        if oid_hex in self._freed:
            raise ObjectLostError(ref.id())
        deadline = None if timeout is None else time.monotonic() + timeout
        reconstruct_attempts = 0

        def remaining():
            if deadline is None:
                return None
            r = deadline - time.monotonic()
            if r <= 0:
                self._failure_event(F.GET_TIMEOUT,
                                    f"timed out resolving {ref}",
                                    oid=oid_hex)
                raise GetTimeoutError(f"timed out resolving {ref}")
            return r

        while True:
            payload = self.memory_store.get(oid_hex)
            if payload is not None:
                return memoryview(payload)
            if self.shared_store:
                view = self.plasma.read(ref.id())
                if view is not None:
                    return view
            if self.memory_store.is_pending(oid_hex):
                if not await self.memory_store.wait_ready(oid_hex, remaining()):
                    self._failure_event(F.GET_TIMEOUT,
                                        f"timed out waiting for {ref}",
                                        oid=oid_hex)
                    raise GetTimeoutError(f"timed out waiting for {ref}")
                continue
            owner = ref.owner_address()
            if owner and owner != self.address:
                try:
                    client = await self._pool.get(owner)
                    reply = await client.call(
                        "get_object", {"oid": oid_hex, "timeout": remaining()},
                        timeout=remaining())
                    if "payload" in reply:
                        return memoryview(reply["payload"])
                    if reply.get("pending"):
                        continue
                    if reply.get("freed"):
                        raise ObjectLostError(ref.id())
                    # in_plasma, or not found in the owner process at all —
                    # either way the location directory decides: the value may
                    # live in another node's store or on spill disk (the owner
                    # can't see its own raylet's spill dir), so fall through
                    # to the raylet pull instead of declaring it lost here.
                except (ConnectionLost, ConnectionError, OSError):
                    cause = F.cause_dict(
                        F.OWNER_DIED,
                        f"owner {owner} unreachable while resolving the "
                        f"object", oid=oid_hex, owner=owner)
                    self._failure_event(F.OWNER_DIED, cause["message"],
                                        oid=oid_hex)
                    raise OwnerDiedError(ref.id(), cause) from None
            # A reconstructable object fails fast on the directory wait —
            # we can rebuild it — while a plain object waits out the caller's
            # deadline in case a producer is still sealing it.
            can_reconstruct = oid_hex in self._lineage
            dir_wait = (min(5.0, remaining() or 5.0) if can_reconstruct
                        else (remaining() or 30.0))
            # Pin across the fetch→read window (reference: ``PinObjectIDs``,
            # ``raylet/node_manager.h:515-555``): concurrent getters' restores
            # must not re-evict this object between the raylet's fetch-ok and
            # our shm read. The raylet refreshes the pin's TTL at fetch-ok,
            # so even a fetch that blocked past the TTL lands protected. Once
            # the view is in hand the mmap stays valid regardless of eviction.
            if not pin_held:
                await self._raylet.call("pin_objects", {"oids": [oid_hex]},
                                        timeout=remaining())
            try:
                reply = await self._raylet.call(
                    "fetch_object", {"oid": oid_hex, "timeout": dir_wait},
                    timeout=remaining())
                if reply.get("ok"):
                    if self.shared_store:
                        view = self.plasma.read(ref.id())
                    else:  # client mode: no shared mmap — RPC download
                        view = await self._download_object(
                            oid_hex, remaining())
                    if view is not None:
                        return view
            finally:
                if not pin_held:
                    spawn_task(self._unpin_quietly([oid_hex]))
            if can_reconstruct and reconstruct_attempts < 2:
                reconstruct_attempts += 1
                await self._reconstruct(oid_hex)
                continue
            owner = ref.owner_address()
            if (owner and owner != self.address
                    and reconstruct_attempts < 2):
                # borrower path: every copy is gone and we hold no lineage —
                # the owner does; ask it to reconstruct
                reconstruct_attempts += 1
                try:
                    client = await self._pool.get(owner)
                    reply = await client.call(
                        "get_object", {"oid": oid_hex, "lost": True},
                        timeout=remaining())
                    if "payload" in reply:
                        return memoryview(reply["payload"])
                    if reply.get("reconstructed"):
                        continue
                except (ConnectionLost, ConnectionError, OSError):
                    pass
            cause = F.cause_dict(
                F.OBJECT_LOST,
                "all copies lost and reconstruction "
                + ("exhausted" if reconstruct_attempts else "unavailable"),
                oid=oid_hex, reconstruct_attempts=reconstruct_attempts)
            self._failure_event(F.OBJECT_LOST, cause["message"], oid=oid_hex)
            raise ObjectLostError(ref.id(), cause)

    def _failure_event(self, category: str, message: str, **fields) -> None:
        """Categorized FailureEvent from this owner process to the GCS
        failure store (`rt errors` / `/api/errors` / the timeline's errors
        lane). Rate-limited per (category, subject) via the shared
        EmitLimiter: a readiness-polling loop of get(timeout=...) expiries
        must not stream one GCS RPC per poll."""
        key = (category, fields.get("oid") or fields.get("actor_id")
               or fields.get("task_id") or message)
        if not self._failure_limiter.allow(key):
            return
        F.emit(self.io.spawn, self._gcs, category, message,
               node_id=self.node_id, **fields)

    async def _report_unreachable_quietly(self, actor_id_hex: str,
                                          address: str) -> None:
        """Best-effort: the GCS itself may be down in exactly this
        scenario — a raised ConnectionError here is noise, not signal."""
        try:
            await self._gcs.call("actor_unreachable", {
                "actor_id": actor_id_hex, "address": address}, timeout=10)
        except Exception:  # noqa: BLE001
            pass

    async def _unpin_quietly(self, oids: List[str]) -> None:
        """Fire-and-forget unpin; a dropped connection (shutdown, raylet
        restart) must not surface as an unretrieved task exception — the
        raylet's pin TTL reclaims the pin anyway."""
        try:
            await self._raylet.call("unpin_objects", {"oids": oids},
                                    timeout=5.0)
        except Exception:  # noqa: BLE001
            pass

    async def _reconstruct(self, oid_hex: str) -> None:
        """Re-execute the creating task to regenerate a lost return object
        (same task_id => same deterministic return ObjectIDs). Concurrent
        getters of the same lost object join one resubmission. Chains
        recover multi-level: the re-executed task's arg resolution runs in
        its worker, whose get falls back to the OWNER of each lost arg with
        ``lost=True`` — and that owner reconstructs from its own lineage
        (reference: recursive recovery, ``object_recovery_manager.h:68-94``)."""
        existing = self._reconstructing.get(oid_hex)
        if existing is not None:
            await asyncio.shield(existing)
            return
        fut = asyncio.get_running_loop().create_future()
        payload = dict(self._lineage[oid_hex])
        payload["reconstruct"] = True
        task_id = TaskID.from_hex(payload["task_id"])
        refs = [ObjectRef(ObjectID.for_return(task_id, i), owner=self.address)
                for i in range(payload["num_returns"])]
        for r in refs:
            self._reconstructing[r.hex()] = fut
        t0 = time.monotonic()
        outcome = "error"
        try:
            target = self._raylet
            if payload.get("pg") is not None:
                target = await self._pg_bundle_raylet(payload["pg"])
            reply = await target.call("submit_task", payload)
            outcome = "failed" if reply.get("error") else "ok"
            self._apply_task_reply(reply, refs, payload["fn_name"], payload)
        finally:
            _observe_reconstruction(outcome, time.monotonic() - t0)
            if outcome != "ok":
                self._failure_event(
                    F.OBJECT_LOST,
                    f"lineage reconstruction of task "
                    f"{payload.get('fn_name')} did not complete "
                    f"({outcome})", oid=oid_hex,
                    task_id=payload.get("task_id"))
            for r in refs:
                self._reconstructing.pop(r.hex(), None)
            if not fut.done():
                fut.set_result(None)

    def _deserialize_result(self, payload: memoryview) -> Any:
        value = self.serde.deserialize_payload(payload)
        if isinstance(value, BaseException):
            raise value
        return value

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        self._notify_blocked()
        if object_ledger.enabled():
            ledger = object_ledger.get_ledger()
            for r in refs:
                ledger.record_get(r.hex())
        # Batched pinning: one pin RPC covers the whole ref set for the
        # duration of the resolve (the per-oid pin in _resolve_payload is
        # skipped). Skipped entirely when every ref is already in our memory
        # store — the hot small-object path pays no raylet round-trip.
        oids = [r.hex() for r in refs]
        all_local = all(self.memory_store.get(h) is not None for h in oids)

        async def _gather():
            if not all_local:
                await self._raylet.call("pin_objects", {"oids": oids},
                                        timeout=timeout)
            try:
                return await asyncio.gather(
                    *[self._resolve_payload(r, timeout,
                                            pin_held=not all_local)
                      for r in refs])
            finally:
                if not all_local:
                    spawn_task(self._unpin_quietly(oids))

        payloads = self.io.run(_gather(), timeout=None if timeout is None
                               else timeout + 5.0)
        if not (tracing.enabled() or tracing.current_context() is not None):
            return [self._deserialize_result(p) for p in payloads]
        # driver_get phase: post-reply deserialization in the caller,
        # attributed per producing task (return objects only — puts carry
        # the high index bit and belong to no task span)
        out: List[Any] = []
        per_task: Dict[str, float] = {}
        for r, p in zip(refs, payloads):
            t0 = time.perf_counter()
            out.append(self._deserialize_result(p))
            oid = r.id()
            if oid.index() < 0x80000000:
                key = oid.task_id().hex()
                per_task[key] = per_task.get(key, 0.0) \
                    + (time.perf_counter() - t0)
        for tid, secs in per_task.items():
            _observe_phases({"driver_get": secs})
            self.io.spawn(self._phase_event(tid, {"driver_get": secs}))
        return out

    def _notify_blocked(self) -> None:
        """Inside a task, a blocking get returns the task's CPU to the raylet
        so children can run (prevents parent-waits-on-child deadlock)."""
        tid = self._current_task_id
        if tid is None or tid in self._blocked_notified:
            return
        self._blocked_notified.add(tid)
        self.io.spawn(self._raylet.call("task_blocked", {"task_id": tid}))

    def wait(self, refs, num_returns, timeout):
        async def _wait():
            futs = {asyncio.ensure_future(self._resolve_payload(r, None)): r
                    for r in refs}
            ready: List[ObjectRef] = []
            deadline = None if timeout is None else time.monotonic() + timeout
            pending = set(futs)
            while len(ready) < num_returns and pending:
                to = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, pending = await asyncio.wait(
                    pending, timeout=to, return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    break
                for f in done:
                    ready.append(futs[f])
            for f in pending:
                f.cancel()
            ready_set = set(ready[:num_returns])
            return ([r for r in refs if r in ready_set],
                    [r for r in refs if r not in ready_set])

        return self.io.run(_wait())

    async def _rpc_get_object(self, p):
        """Serve our memory store to borrowers (long-poll while pending).
        ``lost=True`` from a borrower means every copy is gone: as the owner
        we hold the lineage, so reconstruct before replying (reference: the
        owner drives recovery, ``object_recovery_manager.h``)."""
        oid_hex = p["oid"]
        if oid_hex in self._freed:
            return {"freed": True}
        if self.memory_store.is_pending(oid_hex):
            await self.memory_store.wait_ready(oid_hex, p.get("timeout") or 30.0)
        payload = self.memory_store.get(oid_hex)
        if payload is not None:
            return {"payload": payload}
        if p.get("lost") and oid_hex in self._lineage:
            try:
                await self._reconstruct(oid_hex)
            except Exception:  # noqa: BLE001 — borrower sees not_found
                pass
            payload = self.memory_store.get(oid_hex)
            if payload is not None:
                return {"payload": payload}
            return {"in_plasma": True, "reconstructed": True}
        if self.plasma.contains(ObjectID.from_hex(oid_hex)):
            return {"in_plasma": True}
        return {"not_found": True}

    async def _rpc_stream_subscribe(self, p):
        return await rt_stream.handle_subscribe(self, p)

    # generator streams with a hard small producer-lag bound stay on the
    # acked per-item path: push batching (frame window + producer pump)
    # would loosen the bound `_stream_max_buffer` promises
    _GEN_PUSH_MIN_BUFFER = 16

    async def _rpc_stream_begin(self, p):
        """Streaming-generator push handshake (PR 11's named unclaimed
        stretch): the executor worker registered stream ``sid``; if this
        owner still wants the stream and push is enabled, subscribe a
        one-way frame channel back to the worker and drain it from a
        background task. The legacy acked ``stream_item`` path remains
        the fallback — the worker reverts to it (and redelivers the
        unacked tail, idempotent by index) whenever the channel breaks
        or this reply says no."""
        st = self._streams.get(p["task_id"])
        if st is None or st.closed:
            return {"push": False, "gone": True}
        if (not rt_stream.push_enabled()
                or st.max_buffer < self._GEN_PUSH_MIN_BUFFER):
            return {"push": False}
        # max_buffer is the consumer's MEMORY bound, so it must cover the
        # whole pipeline, not gate each stage independently: half goes to
        # the credit window (channel buffer + producer replay), half to
        # the stored-but-unconsumed gate in the drain task — the producer
        # pump adds window//4 on top, keeping the total within ~1.1x the
        # bound the acked per-item path promises
        window = max(2, st.max_buffer // 2)
        gate = max(1, st.max_buffer - window)
        try:
            ch = await rt_stream.subscribe(self, p["address"], p["sid"],
                                           window=window)
        except Exception:  # noqa: BLE001 — transport hiccup: stay on pull
            return {"push": False}
        if ch is None:
            return {"push": False}
        spawn_task(self._drain_generator_push(st, ch, p["task_id"], gate))
        return {"push": True, "window": window}

    async def _drain_generator_push(self, st: "_StreamState", ch,
                                    task_id_hex: str, gate: int) -> None:
        """Owner half of a pushed generator stream: decode each frame
        ``(index, payload|None)`` into the per-index object slot (the
        exact stores ``_rpc_stream_item`` would have made; plasma items
        were sealed node-side and travel as index-only markers), bounded
        by the same ``max_buffer`` consumer-lag wait. Exits on the done
        frame, on consumer close, on a broken channel (the worker detects
        the stop through the binding and resends the unacked tail over
        the acked path), and on ``st.done`` — when the producer settles
        through the acked fallback no done frame ever arrives, so the
        take must be raced against the stream-state event or this task
        (and the channel endpoint) would park in ``take`` forever."""
        task_id = TaskID.from_hex(task_id_hex)
        take_fut: Optional[asyncio.Future] = None

        def _store(item) -> None:
            idx, payload = item
            if payload is not None:
                self.memory_store.put(
                    ObjectID.for_return(task_id, idx).hex(), payload)
            st.produced = max(st.produced, idx + 1)
            st.notify()

        def _flush_take() -> None:
            # a completed take holds an item the channel already
            # CREDITED as consumed — the producer's fallback excludes
            # acked items from the redelivered tail, so dropping it
            # here would hole the stream permanently
            nonlocal take_fut
            if take_fut is not None and take_fut.done():
                try:
                    item, done = take_fut.result()
                except Exception:  # noqa: BLE001 — broken channel /
                    pass           # error frame: nothing was taken
                else:
                    if not done and item is not None:
                        _store(item)
                take_fut = None

        try:
            while True:
                if st.done or st.closed:
                    # settled via the task reply (the unacked tail was
                    # redelivered by index) or consumer abandon: flush
                    # any credited in-flight take, closed credit stops
                    # the producer
                    _flush_take()
                    ch.close()
                    return
                while (st.produced - st.consumed > gate
                       and not st.done and not st.closed):
                    st._space.clear()
                    await st._space.wait()
                if st.done or st.closed:
                    _flush_take()
                    ch.close()
                    return
                if take_fut is None:
                    take_fut = asyncio.ensure_future(
                        rt_stream.take_decoded(self, ch))
                st._event.clear()
                waiter = asyncio.ensure_future(st._event.wait())
                await asyncio.wait({take_fut, waiter},
                                   return_when=asyncio.FIRST_COMPLETED)
                waiter.cancel()
                if not take_fut.done():
                    continue  # stream-state change: loop re-checks done
                item, done = take_fut.result()
                take_fut = None
                if done:
                    return
                _store(item)
        except ChannelBroken:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — channel already dead
                pass
        except Exception:  # noqa: BLE001 — decode failure: the worker's
            # binding sees the closed channel and falls back to the
            # acked path, which redelivers everything unacked
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass
        finally:
            if take_fut is not None and not take_fut.done():
                take_fut.cancel()

    async def _rpc_stream_item(self, p):
        """Executor pushes one generator item (reference: item reporting,
        ``_raylet.pyx:1090``). Inline payloads land in our memory store;
        plasma items were already sealed node-side. The ack is withheld
        while the consumer lags more than max_buffer items — the executor
        awaits it before producing the next item, which IS the backpressure."""
        st = self._streams.get(p["task_id"])
        if st is None:
            return {"ok": False, "gone": True}  # stream cancelled/unknown
        task_id = TaskID.from_hex(p["task_id"])
        idx = p["index"]
        oid_hex = ObjectID.for_return(task_id, idx).hex()
        if "payload" in p:
            self.memory_store.put(oid_hex, p["payload"])
        st.produced = max(st.produced, idx + 1)
        st.notify()
        while (st.produced - st.consumed > st.max_buffer
               and not st.done and not st.closed):
            st._space.clear()
            await st._space.wait()
        if st.closed:
            return {"ok": False, "gone": True}  # tell the producer to stop
        return {"ok": True}

    def free_objects(self, refs: Sequence[ObjectRef]) -> None:
        ledger = (object_ledger.get_ledger()
                  if object_ledger.enabled() else None)
        for r in refs:
            self.memory_store.delete(r.hex())
            self._lineage.pop(r.hex(), None)
            self._freed[r.hex()] = None
            if ledger is not None:
                ledger.record_freed(r.hex())
        while len(self._freed) > 65536:
            self._freed.pop(next(iter(self._freed)))
        self.io.run(self._raylet.call(
            "free_objects", {"oids": [r.hex() for r in refs]}))

    # ---- function/class export ---------------------------------------------
    def _export(self, kind: str, obj: Any) -> str:
        blob = cloudpickle.dumps(obj)
        fid = f"{kind}:{hashlib.sha1(blob).hexdigest()}"
        if fid not in self._exported_fns:
            self.io.run(self._gcs.call("kv_put", {"key": f"@fn/{fid}",
                                                  "value": blob}))
            self._exported_fns.add(fid)
        return fid

    def load_function(self, fid: str) -> Any:
        fn = self._fn_cache.get(fid)
        if fn is None:
            reply = self.io.run(self._gcs.call("kv_get", {"key": f"@fn/{fid}"}))
            if reply["value"] is None:
                raise RuntimeError(f"function {fid} not found in GCS")
            fn = cloudpickle.loads(reply["value"])
            self._fn_cache[fid] = fn
        return fn

    async def load_function_async(self, fid: str) -> Any:
        fn = self._fn_cache.get(fid)
        if fn is None:
            reply = await self._gcs.call("kv_get", {"key": f"@fn/{fid}"})
            if reply["value"] is None:
                raise RuntimeError(f"function {fid} not found in GCS")
            fn = cloudpickle.loads(reply["value"])
            self._fn_cache[fid] = fn
        return fn

    def _prepare_env(self, options) -> Optional[Dict]:
        """Normalize/upload a runtime_env once per distinct content
        (reference: ``_private/runtime_env/packaging.py`` upload path)."""
        env = options.get("runtime_env")
        if not env:
            return None
        cache_key = json.dumps(env, sort_keys=True, default=str)
        if cache_key not in self._prepared_envs:
            self._prepared_envs[cache_key] = prepare_runtime_env(
                env, self.kv_put, self.kv_get)
        return self._prepared_envs[cache_key]

    @staticmethod
    def _normalize_strategy(options) -> Tuple[Any, Optional[Dict]]:
        """Returns (strategy_spec, pg_info) from the options surface, which
        accepts either scheduling_strategy=PlacementGroupSchedulingStrategy
        or the placement_group=... shorthand."""

        strategy = options.get("scheduling_strategy")
        selector = options.get("label_selector")
        if selector:
            if strategy is not None:
                raise ValueError(
                    "label_selector cannot be combined with "
                    "scheduling_strategy; put soft preferences in a "
                    "NodeLabelStrategy(hard=..., soft=...) instead")
            strategy = NodeLabelStrategy(hard=dict(selector))
        pg = options.get("placement_group")
        if pg is not None:
            if not isinstance(pg, PlacementGroup):
                raise TypeError("placement_group= expects a PlacementGroup")
            strategy = PlacementGroupSchedulingStrategy(
                pg, options.get("placement_group_bundle_index", -1))
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_info = {"pg_id": strategy.placement_group.id.hex(),
                       "bundle_index": strategy.bundle_index}
            return strategy.to_spec(), pg_info
        return strategy, None

    @staticmethod
    def _stamp_overload_options(payload: Dict, options: Dict) -> None:
        """Deadline budget + backpressure policy ride the submit payload
        (absent on the default path — the wire stays small)."""
        if options.get("deadline_s"):
            payload["deadline_s"] = float(options["deadline_s"])
        if options.get("on_overload"):
            payload["on_overload"] = options["on_overload"]

    async def _backpressure_pause(self, attempt: int) -> None:
        """Block-with-backoff between backpressured resubmits: capped
        exponential + jitter so a fleet of throttled producers doesn't
        re-slam the raylet in lockstep."""
        cfg = get_config()
        await asyncio.sleep(F.backoff_with_jitter(
            attempt, cfg.backpressure_retry_base_s,
            cfg.backpressure_retry_max_s))

    def _backpressure_error(self, reply: Dict, fn_name: str):
        return BackpressureError(
            f"task {fn_name} rejected under overload: scheduling-class "
            f"queue at its admission bound "
            f"({reply.get('queue_depth')}/{reply.get('limit')}); the "
            f"default on_overload='block' waits this out instead",
            queue_depth=reply.get("queue_depth"),
            limit=reply.get("limit"))

    def _deadline_shed(self, payload: Dict, what: str):
        """Owner-side pre-enqueue deadline shed: the submit was parked in
        the backpressure backoff loop past its budget and was NEVER
        enqueued, so the owner is the only process that can stamp the
        organic scheduling_timeout feed row (queued work is covered by the
        raylet's ``_evict_item``). Returns ``(message, cause)`` for the
        caller to deliver on its own path (stream slot vs return refs)."""
        msg = (f"deadline_s={payload['deadline_s']} budget expired while "
               f"blocked on backpressure (never enqueued)")
        self._failure_event(
            F.SCHEDULING_TIMEOUT,
            f"{what} {payload['fn_name']} shed: {msg}",
            task_id=payload.get("task_id"),
            name=payload["fn_name"])
        return msg, F.cause_dict(F.SCHEDULING_TIMEOUT,
                                 "deadline expired under backpressure",
                                 task_id=payload.get("task_id"))

    # ---- tasks --------------------------------------------------------------
    def submit_task(self, fn, options, args, kwargs):
        validate_options(options, for_actor=False)
        req = resources_from_options(options, default_num_cpus=1)
        num_returns = options.get("num_returns", 1)
        strategy, pg_info = self._normalize_strategy(options)
        fid = self._export("fn", fn)
        task_id = TaskID.for_task(self.job_id)
        if num_returns == "streaming":
            return self._submit_streaming(fn, options, args, kwargs, req,
                                          strategy, pg_info, fid, task_id)
        refs = [ObjectRef(ObjectID.for_return(task_id, i), owner=self.address)
                for i in range(num_returns)]
        for r in refs:
            self.memory_store.register_pending(r.hex())
        payload = {
            "task_id": task_id.hex(),
            "job_id": self.job_id.hex(),
            "fn_id": fid,
            "fn_name": getattr(fn, "__name__", "anonymous"),
            "args": [self._serialize_arg(a) for a in args],
            "kwargs": {k: self._serialize_arg(v) for k, v in kwargs.items()},
            "num_returns": num_returns,
            "resources": req.to_dict(),
            "strategy": strategy,
            "pg": pg_info,
            "owner": self.address,
            "max_retries": options.get("max_retries",
                                       get_config().task_max_retries_default),
            "runtime_env": self._prepare_env(options),
            "trace": _trace_ctx(),
        }
        self._stamp_overload_options(payload, options)
        self.io.spawn(self._submit_and_collect(
            payload, refs, t_entry=tracing.take_submit_entry()))
        return refs[0] if num_returns == 1 else refs

    def _submit_streaming(self, fn, options, args, kwargs, req, strategy,
                          pg_info, fid, task_id) -> "ObjectRefGenerator":
        """Streaming-generator submission (``num_returns="streaming"``,
        reference: ``remote_function.py:333`` + ``task_manager.h:96``)."""
        state = _StreamState(task_id.hex(), self.address,
                             max_buffer=options.get("_stream_max_buffer", 16),
                             loop=self.loop)
        self._streams[task_id.hex()] = state
        payload = {
            "task_id": task_id.hex(),
            "job_id": self.job_id.hex(),
            "fn_id": fid,
            "fn_name": getattr(fn, "__name__", "anonymous"),
            "args": [self._serialize_arg(a) for a in args],
            "kwargs": {k: self._serialize_arg(v) for k, v in kwargs.items()},
            "num_returns": "streaming",
            "resources": req.to_dict(),
            "strategy": strategy,
            "pg": pg_info,
            "owner": self.address,
            "max_retries": 0,  # raylet-side dedup off; owner drives retries
            "runtime_env": self._prepare_env(options),
            "trace": _trace_ctx(),  # span + phases land via the raylet
        }
        self._stamp_overload_options(payload, options)

        async def _run():
            # A stream that produced NOTHING yet is safe to retry whole
            # (transient worker-spawn failures under load); once items have
            # been consumed, a partial stream must not silently re-run.
            retries = get_config().task_max_retries_default
            bp_attempts = 0
            bp_deadline = (time.monotonic() + payload["deadline_s"]
                           if payload.get("deadline_s") else None)
            while True:
                try:
                    target = self._raylet
                    if payload.get("pg") is not None:
                        target = await self._pg_bundle_raylet(payload["pg"])
                    reply = await target.call("submit_task", payload)
                except Exception as e:
                    reply = {"error": "submit_failed", "message": repr(e)}
                if reply.get("error") == "backpressure":
                    if (bp_deadline is not None
                            and time.monotonic() >= bp_deadline):
                        # deadline holds pre-enqueue: shed instead of
                        # blocking past the budget
                        msg, cause = self._deadline_shed(payload, "stream")
                        reply = {"error": "deadline_exceeded",
                                 "message": msg, "cause": cause}
                    elif (payload.get("on_overload") != "fail"
                            and not state.closed):
                        bp_attempts += 1
                        await self._backpressure_pause(bp_attempts)
                        continue
                if (reply.get("error") in ("worker_crashed", "bundle_gone",
                                           "submit_failed", "oom_killed")
                        and state.produced == 0 and not state.closed
                        and retries > 0):
                    retries -= 1
                    _observe_retry()
                    continue
                break
            if reply.get("error"):
                if reply["error"] == "backpressure":
                    err: Exception = self._backpressure_error(
                        reply, payload["fn_name"])
                elif reply["error"] == "deadline_exceeded":
                    err = SchedulingTimeoutError(
                        f"streaming task {payload['fn_name']} shed: "
                        f"{reply.get('message', reply['error'])}",
                        cause=reply.get("cause"))
                else:
                    err = WorkerCrashedError(
                        f"streaming task {payload['fn_name']} failed: "
                        f"{reply.get('message', reply['error'])}")
                blob = self.serde.serialize(err).to_bytes()
                idx = state.produced
                self.memory_store.put(
                    ObjectID.for_return(task_id, idx).hex(), blob)
                state.produced = idx + 1
            elif reply.get("stream_error") is not None:
                idx = state.produced
                self.memory_store.put(
                    ObjectID.for_return(task_id, idx).hex(),
                    reply["stream_error"])
                state.produced = idx + 1
            state.done = True
            state.notify()
            # state is kept for iteration; dropped when consumed or replaced
            if len(self._streams) > 1024:
                for k in [k for k, s in self._streams.items()
                          if s.done and s.consumed >= s.produced][:512]:
                    self._streams.pop(k, None)

        self.io.spawn(_run())
        return ObjectRefGenerator(self, state)

    async def _submit_and_collect(self, payload, refs: List[ObjectRef],
                                  t_entry: Optional[float] = None) -> None:
        retries = payload.get("max_retries", 0)
        attempt = 0
        bp_attempts = 0
        # the deadline budget must hold PRE-enqueue too: a submit parked in
        # the backpressure backoff loop is exactly the stale work
        # deadline_s exists to shed
        bp_deadline = (time.monotonic() + payload["deadline_s"]
                       if payload.get("deadline_s") else None)
        traced = payload.get("trace") is not None  # one predicate per hop
        while True:
            t_sub = (t_entry if attempt == 0 and t_entry is not None
                     else time.perf_counter()) if traced else 0.0
            try:
                target = self._raylet
                if payload.get("pg") is not None:
                    target = await self._pg_bundle_raylet(payload["pg"])
                reply = await target.call("submit_task", payload)
            except Exception as e:
                reply = {"error": "submit_failed", "message": repr(e)}
            if reply.get("error") == "backpressure":
                # admission control bounced the submit: block-with-backoff
                # (default) keeps the producer paced without consuming its
                # retry budget; fail-fast resolves the refs with a
                # BackpressureError the caller can catch.
                if payload.get("on_overload") == "fail":
                    blob = self.serde.serialize(self._backpressure_error(
                        reply, payload["fn_name"])).to_bytes()
                    for r in refs:
                        self.memory_store.put(r.hex(), blob)
                    return
                if (bp_deadline is not None
                        and time.monotonic() >= bp_deadline):
                    msg, cause = self._deadline_shed(payload, "task")
                    err = SchedulingTimeoutError(
                        f"task {payload['fn_name']} shed: {msg}",
                        cause=cause)
                    blob = self.serde.serialize(err).to_bytes()
                    for r in refs:
                        self.memory_store.put(r.hex(), blob)
                    return
                bp_attempts += 1
                await self._backpressure_pause(bp_attempts)
                continue
            if reply.get("error") in ("worker_crashed", "bundle_gone",
                                      "submit_failed", "oom_killed"):
                if payload.get("pg") is not None:
                    self._pg_addr_cache.pop(
                        (payload["pg"]["pg_id"],
                         payload["pg"].get("bundle_index", -1)), None)
                if attempt < retries:
                    attempt += 1
                    _observe_retry()
                    continue
            break
        if traced and reply.get("phases") is not None:
            # FINAL attempt only — a retried attempt's partial phases must
            # not double-count the task in the histogram or pollute the
            # event's merged breakdown. submit = driver-side residual of
            # this attempt's wall around the raylet's accounted interval
            # (serialization + both RPC directions); completes the
            # partition.
            reply["phases"]["submit"] = max(
                0.0, (time.perf_counter() - t_sub)
                - reply.get("phases_total", 0.0))
            _observe_phases(reply["phases"])
            spawn_task(self._phase_event(
                payload["task_id"],
                {"submit": reply["phases"]["submit"]}))
        self._apply_task_reply(reply, refs, payload["fn_name"], payload)

    async def _phase_event(self, task_id_hex: str,
                           phases: Dict[str, float]) -> None:
        """Merge driver-measured phases (submit, driver_get) into the
        task's GCS event; best-effort, fire-and-forget. No state/node_id:
        a partial merge must not flip what the raylet recorded (a FAILED
        task stays FAILED)."""
        try:
            await self._gcs.call("task_event", {
                "task_id": task_id_hex, "phases": phases,
                "times": {"DRIVER": time.time()}}, timeout=10)
        except Exception:  # noqa: BLE001
            pass

    async def _pg_bundle_raylet(self, pg_info: Dict):
        """Resolve the raylet hosting the task's bundle. The address of a
        pinned bundle is cached after first resolution (invalidated on
        bundle_gone) so steady-state PG task submission costs zero extra
        control-plane round-trips."""
        idx = pg_info.get("bundle_index", -1)
        if idx >= 0:
            cached = self._pg_addr_cache.get((pg_info["pg_id"], idx))
            if cached is not None:
                return await self._pool.get(cached)
        await self._gcs.call("wait_placement_group", {
            "pg_id": pg_info["pg_id"], "timeout": 300.0})
        reply = await self._gcs.call("get_placement_group", {
            "pg_id": pg_info["pg_id"], "pick_bundle": True,
            "bundle_index": idx})
        if reply.get("error") or reply.get("picked_address") is None:
            raise RuntimeError(
                f"placement group unavailable: {reply.get('error', reply.get('state'))}")
        pg_info["bundle_index"] = reply["picked_bundle"]
        self._pg_addr_cache[(pg_info["pg_id"], reply["picked_bundle"])] = \
            reply["picked_address"]
        return await self._pool.get(reply["picked_address"])

    def _apply_task_reply(self, reply, refs: List[ObjectRef], fn_name: str,
                          payload: Optional[Dict] = None) -> None:
        if reply.get("error"):
            msg = f"task {fn_name} failed: {reply.get('message', reply['error'])}"
            if reply["error"] == "oom_killed":
                err: Exception = OutOfMemoryError(msg)
            elif reply["error"] == "deadline_exceeded":
                # the raylet shed the task (deadline_s budget expired in
                # queue); get() raises the scheduling_timeout cause
                err = SchedulingTimeoutError(msg, cause=reply.get("cause"))
            elif reply["error"] == "backpressure":
                # only reachable on paths that bypass the submit loop's
                # own backpressure handling (e.g. reconstruction)
                err = self._backpressure_error(reply, fn_name)
            else:
                err = WorkerCrashedError(msg)
            # the raylet's structured cause rides into the raised exception
            # (picklable: BaseException reduce carries __dict__), so `rt
            # errors` and the get()-time error agree on why
            if reply.get("cause"):
                err.cause_info = dict(reply["cause"])
            if reply["error"] == "submit_failed":
                # the raylet never saw this task — the owner is the only
                # process that can put it on the failure feed
                self._failure_event(
                    F.WORKER_CRASH, msg,
                    task_id=payload.get("task_id") if payload else None,
                    name=fn_name)
            blob = self.serde.serialize(err).to_bytes()
            for r in refs:
                self.memory_store.put(r.hex(), blob)
            return
        returns = reply.get("returns", [])
        for r, ret in zip(refs, returns):
            kind, data = ret
            if kind == "val":
                self.memory_store.put(r.hex(), data)
                self._lineage.pop(r.hex(), None)
            else:  # "plasma": sealed by the executor; location registered
                self.memory_store.mark_external(r.hex())
                if payload is not None:
                    # retain lineage so this return can be rebuilt if every
                    # copy is lost (bounded: oldest entries dropped)
                    self._lineage[r.hex()] = payload
                    while len(self._lineage) > 4096:
                        self._lineage.pop(next(iter(self._lineage)))

    # ---- actors -------------------------------------------------------------
    def create_actor(self, cls, options, args, kwargs, method_meta):
        validate_options(options, for_actor=True)
        req = resources_from_options(options, default_num_cpus=0)
        strategy, pg_info = self._normalize_strategy(options)
        cid = self._export("cls", cls)
        actor_id = ActorID.of(self.job_id)
        spec = {
            "actor_id": actor_id.hex(),
            "job_id": self.job_id.hex(),
            "class_id": cid,
            "class_name": cls.__name__,
            "args": [self._serialize_arg(a) for a in args],
            "kwargs": {k: self._serialize_arg(v) for k, v in kwargs.items()},
            "resources": req.to_dict(),
            "max_restarts": options.get("max_restarts", 0),
            "max_task_retries": options.get("max_task_retries", 0),
            "max_concurrency": options.get("max_concurrency") or 1,
            "concurrency_groups": options.get("concurrency_groups") or {},
            "name": options.get("name"),
            "namespace": options.get("namespace") or self.namespace,
            "lifetime": options.get("lifetime"),
            "get_if_exists": options.get("get_if_exists", False),
            "scheduling_strategy": strategy,
            "pg": pg_info,
            "method_meta": method_meta,
            "owner": self.address,
            "runtime_env": self._prepare_env(options),
        }
        reply = self.io.run(self._gcs.call("register_actor", {"spec": spec}))
        if reply.get("error"):
            raise ValueError(reply["error"])
        if reply.get("existing"):
            return ActorHandle(ActorID.from_hex(reply["actor_id"]),
                               cls.__name__, dict(reply["method_meta"] or {}))
        return ActorHandle(actor_id, cls.__name__, method_meta,
                           original_handle=True)

    def _actor_conn(self, actor_id_hex: str) -> _ActorConn:
        conn = self._actor_conns.get(actor_id_hex)
        if conn is None:
            conn = _ActorConn(actor_id_hex)
            conn.send_lock = asyncio.Lock()
            self._actor_conns[actor_id_hex] = conn
        return conn

    async def _resolve_actor(self, conn: _ActorConn, timeout: float = 60.0,
                             deadline: Optional[float] = None) -> str:
        # PENDING_CREATION / RESTARTING are NOT errors: the actor may be
        # queued behind cluster resources (or a node the autoscaler is
        # still provisioning). Like the reference, callers block until it
        # comes alive or genuinely dies — with a periodic warning so an
        # infeasible request is visible instead of a silent hang. An
        # optional deadline (param or RT_ACTOR_RESOLVE_DEADLINE_S) bounds
        # the wait with a distinct ActorUnschedulableError.
        if deadline is None:
            deadline = get_config().actor_resolve_deadline_s or None
        waited = 0.0
        while True:
            # clamp each long-poll to the remaining deadline so a short
            # deadline isn't swallowed by one 60s GCS wait
            poll = timeout if deadline is None else max(
                0.5, min(timeout, deadline - waited))
            reply = await self._gcs.call("get_actor_info", {
                "actor_id": conn.actor_id_hex, "wait_alive": True,
                "timeout": poll})
            info = reply.get("info")
            if info is None:
                raise ActorDiedError(conn.actor_id_hex, "unknown actor")
            if info["state"] == "DEAD":
                # the GCS knows MORE than a bare reason string: surface the
                # structured cause (category, restart count, last node) so
                # the caller-side error says what `rt list actors` knows
                conn.dead_reason = info.get("death_reason", "dead")
                conn.dead_cause = info.get("death_cause") or {
                    "category": F.UNKNOWN, "message": conn.dead_reason,
                    "num_restarts": info.get("num_restarts"),
                    "node_id": info.get("node_id")}
                raise ActorDiedError(conn.actor_id_hex, conn.dead_reason,
                                     cause=conn.dead_cause)
            if info["state"] == "ALIVE":
                break
            waited += poll
            if deadline is not None and waited >= deadline:
                raise ActorUnschedulableError(conn.actor_id_hex,
                                              info["state"], waited)
            logger.warning(
                "actor %s still %s after %.0fs — waiting for cluster "
                "resources (creation queues until a node frees up or "
                "the autoscaler adds capacity; check requested "
                "num_cpus/num_tpus against the cluster)",
                conn.actor_id_hex, info["state"], waited)
        conn.address = info["address"]
        conn.max_task_retries = info.get("max_task_retries", 0)
        return conn.address

    def submit_actor_task(self, actor_id: ActorID, method_name, args, kwargs,
                          num_returns: int = 1):
        task_id = TaskID.for_actor_task(actor_id)
        refs = [ObjectRef(ObjectID.for_return(task_id, i), owner=self.address)
                for i in range(num_returns)]
        for r in refs:
            self.memory_store.register_pending(r.hex())
        payload = {
            "actor_id": actor_id.hex(),
            "task_id": task_id.hex(),
            "method": method_name,
            "args": [self._serialize_arg(a) for a in args],
            "kwargs": {k: self._serialize_arg(v) for k, v in kwargs.items()},
            "num_returns": num_returns,
            "owner": self.address,
            "trace": _trace_ctx(),
        }
        self.io.spawn(self._submit_actor_and_collect(
            payload, refs, method_name,
            t_entry=tracing.take_submit_entry()))
        return refs[0] if num_returns == 1 else refs

    async def _submit_actor_and_collect(self, payload, refs, method_name,
                                        t_entry: Optional[float] = None
                                        ) -> None:
        conn = self._actor_conn(payload["actor_id"])
        # Delivery semantics (reference parity, actor.py:333-352): connection
        # failures BEFORE the call is written are always safe to retry; once
        # delivered, a lost connection fails the call unless the actor was
        # created with max_task_retries > 0 (the call may have side effects).
        task_retries_left: Optional[int] = None
        connect_attempts = 0
        while True:
            try:
                # The send lock makes submission order == delivery order per
                # caller (reference: SequentialActorSubmitQueue); execution
                # ordering is the actor worker's arrival-ordered queue.
                async with conn.send_lock:
                    if conn.dead_reason:
                        raise ActorDiedError(payload["actor_id"],
                                             conn.dead_reason,
                                             cause=conn.dead_cause)
                    if conn.address is None:
                        await self._resolve_actor(conn)
                    if task_retries_left is None:
                        task_retries_left = conn.max_task_retries
                    try:
                        client = await self._pool.get(conn.address)
                    except (ConnectionLost, ConnectionError, OSError):
                        # Never delivered — free retry (actor restarting).
                        # Tell the GCS: if the actor's node is gone (e.g.
                        # state restored across a head restart with a stale
                        # address), this triggers the restart path NOW
                        # instead of us spinning against a dead address.
                        spawn_task(self._report_unreachable_quietly(
                            payload["actor_id"], conn.address))
                        conn.address = None
                        connect_attempts += 1
                        if connect_attempts > 10:
                            raise ActorDiedError(payload["actor_id"],
                                                 "unreachable") from None
                        await asyncio.sleep(get_config().actor_restart_backoff_s)
                        continue
                    t_sub = 0.0
                    if payload.get("trace") is not None:
                        t_sub = (t_entry if t_entry is not None
                                 else time.perf_counter())
                        t_entry = None  # retries re-stamp from now
                    fut = asyncio.ensure_future(
                        client.call("actor_call", payload))
                reply = await fut
                worker_phases = reply.pop("worker_phases", None)
                if payload.get("trace") is not None and worker_phases:
                    # actor calls bypass the raylet: the partition is just
                    # worker-side phases + the driver's submit residual
                    phases = dict(worker_phases)
                    phases["submit"] = max(
                        0.0, (time.perf_counter() - t_sub)
                        - sum(worker_phases.values()))
                    reply["phases"] = phases
                    _observe_phases(phases)
                    spawn_task(self._phase_event(
                        payload["task_id"], {"submit": phases["submit"]}))
                self._apply_task_reply(reply, refs, method_name)
                return
            except (ActorDiedError, ActorUnschedulableError) as e:
                # both resolve the caller's refs with the error so get()
                # re-raises it instead of hanging on a never-sent call
                blob = self.serde.serialize(e).to_bytes()
                for r in refs:
                    self.memory_store.put(r.hex(), blob)
                return
            except (ConnectionLost, ConnectionError, OSError):
                conn.address = None  # delivered but connection dropped
                if task_retries_left and task_retries_left > 0:
                    task_retries_left -= 1
                    _observe_retry()
                    await asyncio.sleep(get_config().actor_restart_backoff_s)
                    continue
                err = ActorDiedError(
                    payload["actor_id"],
                    f"connection lost during {method_name!r} (actor died or "
                    f"restarting); set max_task_retries to retry actor tasks",
                    cause=F.cause_dict(
                        F.WORKER_CRASH,
                        f"connection lost during {method_name!r}",
                        actor_id=payload["actor_id"]))
                blob = self.serde.serialize(err).to_bytes()
                for r in refs:
                    self.memory_store.put(r.hex(), blob)
                return
            except Exception as e:  # noqa: BLE001 — worker-side RPC error
                # e.g. concurrency-group validation, misrouted method: the
                # server errored the call. This coroutine is fire-and-forget,
                # so an uncaught raise would STRAND the caller's refs — the
                # error must flow into them instead.
                blob = self.serde.serialize(e).to_bytes()
                for r in refs:
                    self.memory_store.put(r.hex(), blob)
                return

    def kill_actor(self, actor_id: ActorID, no_restart: bool) -> None:
        conn = self._actor_conns.get(actor_id.hex())
        if conn:
            conn.address = None
            conn.dead_reason = "killed via kill()"
            conn.dead_cause = F.cause_dict(F.CANCELLED, "killed via kill()",
                                           actor_id=actor_id.hex())
        self.io.run(self._gcs.call("kill_actor", {"actor_id": actor_id.hex()}))

    def get_actor_handle(self, name, namespace):
        reply = self.io.run(self._gcs.call("get_named_actor", {
            "name": name, "namespace": namespace or self.namespace}))
        if reply.get("error"):
            raise ValueError(reply["error"])
        return ActorHandle(ActorID.from_hex(reply["actor_id"]),
                           reply["info"]["class_name"],
                           dict(reply["method_meta"] or {}))

    # ---- cluster info / kv --------------------------------------------------
    def cancel(self, ref, force=False):
        pass  # cooperative cancellation lands with the lease redesign

    def cluster_resources(self):
        return self.io.run(self._gcs.call("cluster_resources", {}))["total"]

    def available_resources(self):
        return self.io.run(self._gcs.call("cluster_resources", {}))["available"]

    def nodes(self):
        return self.io.run(self._gcs.call("list_nodes", {}))

    def kv_put(self, key, value):
        self.io.run(self._gcs.call("kv_put", {"key": key, "value": value}))

    def kv_get(self, key):
        return self.io.run(self._gcs.call("kv_get", {"key": key}))["value"]

    def kv_del(self, key):
        self.io.run(self._gcs.call("kv_del", {"key": key}))

    def kv_keys(self, prefix):
        return self.io.run(self._gcs.call("kv_keys", {"prefix": prefix}))["keys"]

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        if object_ledger.enabled():
            # a dead process's KV ledger snapshot must not keep reporting
            # its objects as held (workers killed outright are covered by
            # the staleness filter in util/memory._kv_ledgers)
            object_ledger.get_ledger().retract(self)
        hook = self._cluster_shutdown_hook
        if hook is not None:
            try:
                hook()
            except Exception:
                pass
        try:
            self.io.run(self.server.stop(), timeout=2)
        except Exception:
            pass
        self.io.stop()
