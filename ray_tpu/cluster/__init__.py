"""Multiprocess cluster runtime.

The process tree mirrors the reference's (SURVEY.md §3.1): a head with the
GCS (cluster control plane) and a raylet (node control plane) per node;
worker processes leased from the raylet execute tasks and host actors; a
shared-memory object store per node gives zero-copy reads; owners serve
small objects from an in-process memory store. Transport is asyncio TCP with
pickled frames (the gRPC role); the data plane between collocated processes
is /dev/shm.
"""
