# rt: hot-module
"""Push-stream plumbing over the rpc layer's one-way frames.

The per-token-RPC killer (ROADMAP item 1; reference: Ray core's streaming
generators pushing results over the worker's persistent connection,
arxiv 1712.05889): a producer process registers a stream *source* here;
the consumer opens a :class:`~ray_tpu.cluster.rpc.StreamChannel` on its
existing pooled connection and sends ONE ``stream_subscribe`` RPC; after
that every token burst rides a one-way ``_PUSH`` frame — no reply slot,
no polling executor thread, no per-burst actor RPC. Credit frames
(cumulative consumed count) bound the producer: at ``window`` unacked
items the pump parks, so a slow consumer backpressures the producer
instead of ballooning memory on either side.

Reliability: every pushed-but-unacked item stays in a replay buffer
(bounded by the window). When the connection drops — or chaos breaks the
channel — the consumer falls back to the pull path: ``resume_pull``
reclaims the replay tail by the consumer's delivered count, so the
stream completes token-exact through the fallback.

Object plane: frame items are inline python values; byte-payloads over
``RT_STREAM_INLINE_MAX`` spill to the node's plasma store and travel as
an oid reference — a same-node consumer mmaps them zero-copy (the
pickle-5 path in ``object_store.py``).
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.cluster.rpc import (
    CHANNEL_DONE,
    ChannelBroken,
    ConnectionLost,
    ServerConnection,
    StreamChannel,
    current_server_connection,
    spawn_task,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.worker import global_worker
from ray_tpu.util import chaos as _chaos
from ray_tpu.util import metrics as M

__all__ = [
    "register_source", "unregister_source", "reclaim", "drain_source",
    "settle_source", "peek_unacked", "push_enabled", "subscribe",
    "take_decoded", "handle_subscribe", "stream_window",
    "observe_request_rpcs", "count_pull_frames",
]

_PUMP_BATCH = 64

# frame item kinds on the wire: ("v", value) inline, ("o", descriptor,
# nbytes) plasma reference, ("e", serialized_exception) error transport
_KIND_VAL, _KIND_OID, _KIND_ERR = "v", "o", "e"


def push_enabled() -> bool:
    """Consumer-side default transport. ``RT_STREAM_PULL=1`` keeps the
    PR 9 pull pool as the primary path (fallback/rescue knob)."""
    return os.environ.get("RT_STREAM_PULL", "") != "1"


def stream_window() -> int:
    return int(os.environ.get("RT_STREAM_WINDOW", "128"))


def inline_max_bytes() -> int:
    """Byte payloads above this spill to plasma and travel by reference
    (same-node consumers mmap them zero-copy)."""
    return int(os.environ.get("RT_STREAM_INLINE_MAX", str(64 * 1024)))


# ---------------------------------------------------------------------------
# metrics (lazy — the registry must not be touched at import time)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Dict[str, Any] = {}  # rt: guarded-by(_metrics_lock)

_RPC_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128)


def _metric(key: str, factory: Callable[[], Any]) -> Any:
    with _metrics_lock:
        m = _metrics.get(key)
        if m is None:
            m = _metrics[key] = factory()
        return m


def frames_total() -> "M.Counter":
    return _metric("frames", lambda: M.get_or_create(
        M.Counter, "rt_stream_frames_total",
        "Stream frame batches moved, by transport (push = one-way "
        "frames, pull = next_chunks RPC batches)",
        tag_keys=("transport",)))


def bytes_total() -> "M.Counter":
    return _metric("bytes", lambda: M.get_or_create(
        M.Counter, "rt_stream_bytes_total",
        "Wire bytes of pushed stream frames (producer side, serialized "
        "frame size)", tag_keys=("transport",)))


def rpcs_per_request() -> "M.Histogram":
    return _metric("rpcs", lambda: M.get_or_create(
        M.Histogram, "rt_stream_rpcs_per_request",
        "RPCs a consumer issued to drain one response stream "
        "(push path: O(1) per request regardless of token count)",
        tag_keys=("transport",), boundaries=_RPC_BUCKETS))


def observe_request_rpcs(transport: str, n: int) -> None:
    """Consumer-side: one observation per completed/cancelled stream."""
    try:
        rpcs_per_request().observe(n, tags={"transport": transport})
    except Exception:  # noqa: BLE001 — telemetry never fails the stream
        pass


def count_pull_frames(n_items: int) -> None:
    """Producer-side accounting for the pull path (one non-empty
    next_chunks batch == one frame on the ``transport="pull"`` series;
    bytes are measured for push only — pull batches are RPC replies
    whose wire size this layer never sees)."""
    if n_items <= 0:
        return
    try:
        frames_total().inc(1.0, {"transport": "pull"})
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# producer side: source registry + push binding
# ---------------------------------------------------------------------------


class _RegisteredSource:
    """One pushable stream in this process. ``pump`` provides
    ``async take(max_items) -> (items, done)`` and ``close()``;
    ``on_done`` runs when the stream fully completes over push
    (the replica uses it to release the in-flight slot)."""

    def __init__(self, sid: str, pump: Any,
                 on_done: Optional[Callable[[], None]]):
        self.sid = sid
        self.pump = pump
        self.on_done = on_done
        self.binding: Optional[_PushBinding] = None


_reg_lock = threading.Lock()
_sources: Dict[str, _RegisteredSource] = {}  # rt: guarded-by(_reg_lock)


def register_source(sid: str, pump: Any,
                    on_done: Optional[Callable[[], None]] = None) -> None:
    with _reg_lock:
        _sources[sid] = _RegisteredSource(sid, pump, on_done)


def unregister_source(sid: str) -> None:
    """Drop the source (cancel / stream finished via pull). Stops a live
    push pump; does NOT close the pump (the stream owner does that)."""
    with _reg_lock:
        rs = _sources.pop(sid, None)
    if rs is not None and rs.binding is not None:
        rs.binding.request_stop()


async def reclaim(sid: str, delivered: int
                  ) -> Tuple[List[Any], bool, Optional[BaseException]]:
    """Pull-fallback handoff: detach the push binding and return the
    replay tail past the consumer's ``delivered`` count, plus whether the
    source was already exhausted and any pending stream error.
    Runs on the producer's event loop (async actor method).

    Await-the-pump matters: the pump task may be blocked INSIDE
    ``pump.take`` right now — the items that take returns are stashed
    into the replay buffer only when it lands, so snapshotting the
    buffer without waiting would silently drop an in-flight burst
    (observed as a one-token hole at the fallback boundary)."""
    with _reg_lock:
        rs = _sources.get(sid)
    if rs is None or rs.binding is None:
        return ([], False, None)
    binding, rs.binding = rs.binding, None
    binding.request_stop()
    try:
        await asyncio.wait_for(binding.wait_finished(), timeout=60.0)
    except asyncio.TimeoutError:
        pass  # wedged source: serve what the buffer has
    items = [it for seq, it in binding.replay if seq >= delivered]
    err: Optional[BaseException] = None
    if binding.error_payload is not None:
        decoded = binding.backend.serde.deserialize_payload(
            memoryview(binding.error_payload))
        err = (decoded if isinstance(decoded, BaseException)
               else RuntimeError(f"stream failed: {decoded!r}"))
    return (items, binding.source_done, err)


async def drain_source(sid: str, delivered: int
                       ) -> Tuple[List[Any], bool, Optional[BaseException]]:
    """One-shot pull fallback for FINITE sources (weight shipments):
    :func:`reclaim` the pushed-but-undelivered tail, then drain the
    pump to exhaustion and deregister the source. Returns
    ``(items, known, error)`` — ``known`` False when ``sid`` names no
    registered source and nothing was replayed (spent or never
    shipped). Unlike the serve path's resume_pull (which keeps the
    stream open for further next_chunks pulls), this settles the whole
    stream in one reply. Runs on the producer's event loop."""
    items, done, err = await reclaim(sid, delivered)
    if err is not None:
        unregister_source(sid)
        return (items, True, err)
    with _reg_lock:
        rs = _sources.get(sid)
    if rs is None and not items and not done:
        return ([], False, None)
    pump = rs.pump if rs is not None else None
    while pump is not None and not done:
        more, done = await pump.take(_PUMP_BATCH)
        items.extend(more)
    unregister_source(sid)
    return (items, True, None)


async def settle_source(sid: str, grace_s: float = 5.0
                        ) -> Optional[List[Any]]:
    """Producer-side settlement for FINITE pushed streams (the
    streaming-generator path): wait for the pump to land and (briefly)
    for the consumer's final credit. Returns None when the stream
    COMPLETED over push (every item acked — nothing left to do), else
    the unacked tail items to redeliver over the legacy acked path
    (redelivery is idempotent there: the owner stores by index). Always
    deregisters the source on the non-completed path. Runs on the
    producer's event loop."""
    with _reg_lock:
        rs = _sources.get(sid)
    if rs is None:
        return None  # already completed (on_done popped it)
    binding = rs.binding
    if binding is None:
        rt_items = []  # registered but never subscribed: nothing pushed
        unregister_source(sid)
        return rt_items
    await binding.wait_finished()
    deadline = asyncio.get_running_loop().time() + grace_s
    while (not binding.completed and binding.source_done
           and binding.conn.alive and not binding._stop
           and asyncio.get_running_loop().time() < deadline):
        await asyncio.sleep(0.02)
    if binding.completed:
        return None
    tail = [it for seq, it in binding.replay if seq >= binding.acked]
    unregister_source(sid)
    return tail


def peek_unacked(sid: str) -> List[Any]:
    """Producer-THREAD escape hatch for a wedged event loop: a racy
    snapshot of ``sid``'s pushed-but-unacked replay items. The replay
    deque is loop-confined, so reading it off-loop can at worst observe
    a stale acked watermark and over-return — safe for the generator
    path, where redelivery is idempotent by index; dropping a
    pushed-but-unacked item would hole the stream instead."""
    with _reg_lock:
        rs = _sources.get(sid)
    binding = rs.binding if rs is not None else None
    if binding is None:
        return []
    for _ in range(50):
        try:
            acked = binding.acked
            return [it for seq, it in list(binding.replay) if seq >= acked]
        except RuntimeError:
            # deque mutated mid-snapshot (loop overloaded, not dead):
            # retry — raising here would fail the very task this
            # fallback exists to rescue
            time.sleep(0.01)
    return []


class _PushBinding:
    """Producer half of one subscribed channel: the pump task, the
    credit window, and the replay buffer fallback reclaims from.
    All state is confined to the producer's event loop (credits arrive
    on the server read loop, the pump runs as a sibling task)."""

    def __init__(self, backend, rs: _RegisteredSource,
                 conn: ServerConnection, channel_id: str, window: int):
        self.backend = backend
        self.rs = rs
        self.conn = conn
        self.channel_id = channel_id
        self.window = max(2, window)
        self.sent = 0
        self.acked = 0
        self.replay: deque = deque()  # (seq, item) pushed but unacked
        self.source_done = False      # pump exhausted the source
        self.error_payload: Optional[bytes] = None
        self.completed = False
        self._stop = False
        self._credit_event = asyncio.Event()
        self._finished = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = spawn_task(self._run_pump())

    async def _run_pump(self) -> None:
        try:
            await self._pump()
        finally:
            # reclaim synchronizes on this: the replay buffer is only
            # complete once the pump (and any in-flight take) has landed
            self._finished.set()

    async def wait_finished(self) -> None:
        await self._finished.wait()

    # -- endpoint interface (called from the server read loop) ------------
    def on_credit(self, consumed: int, closed: bool) -> None:
        if consumed > self.acked:
            self.acked = consumed
            while self.replay and self.replay[0][0] < self.acked:
                self.replay.popleft()
        if closed:
            # "stop pushing": completion when everything was consumed,
            # otherwise a fallback/cancel detach — the stream itself is
            # settled by resume_pull or cancel_stream, not by this frame
            self._stop = True
            if self.source_done and self.acked >= self.sent:
                self._complete()
            else:
                self._notify_pump_stop()
        self._credit_event.set()

    def on_disconnect(self) -> None:
        self._stop = True
        self._notify_pump_stop()
        self._credit_event.set()

    def request_stop(self) -> None:
        """Safe from any thread (cancel_stream runs on executor threads):
        the event wakeup is routed to the producer's loop."""
        self._stop = True
        self._notify_pump_stop()
        loop = self.backend.loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self._credit_event.set()
        elif not loop.is_closed():
            loop.call_soon_threadsafe(self._credit_event.set)

    def _notify_pump_stop(self) -> None:
        """A detached/broken binding may strand a pump ``take`` parked on
        a quiet source: pumps exposing ``binding_stopped()`` (the
        streaming-generator push pump) are woken so the producer thread
        can settle and fall back. Serve pumps don't define it — their
        settlement runs through reclaim/resume_pull instead."""
        fn = getattr(self.rs.pump, "binding_stopped", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — wake is best-effort
                pass

    def _complete(self) -> None:
        """The consumer saw the final frame and acked every item: settle
        the stream (release the replica slot) exactly once."""
        if self.completed:
            return
        self.completed = True
        with _reg_lock:
            _sources.pop(self.rs.sid, None)
        if self.rs.on_done is not None:
            try:
                self.rs.on_done()
            except Exception:  # noqa: BLE001 — owner callback
                pass

    # -- the pump ---------------------------------------------------------
    async def _pump(self) -> None:
        try:
            while not self._stop:
                # credit window: park until the consumer catches up
                while (self.sent - self.acked >= self.window
                       and not self._stop):
                    self._credit_event.clear()
                    await self._credit_event.wait()
                if self._stop:
                    return
                try:
                    items, done = await self.rs.pump.take(
                        min(_PUMP_BATCH, self.window))
                except asyncio.CancelledError:
                    raise
                except BaseException as e:  # noqa: BLE001 — error transport
                    # the stream's failure travels as a final error frame
                    self.error_payload = \
                        self.backend.serde.serialize(e).to_bytes()
                    self.source_done = True
                    await self._push([(_KIND_ERR, self.error_payload)],
                                     done=True)
                    return
                if self._stop:
                    # stopped while blocked in take(): the taken items
                    # must not vanish — stash them for reclaim
                    for it in items:
                        self.replay.append((self.sent, it))
                        self.sent += 1
                    self.source_done = self.source_done or done
                    return
                wire = []
                try:
                    for it in items:
                        # replay BEFORE encode: a failing plasma spill
                        # (raylet hiccup mid-encode) must leave the item
                        # reclaimable, not silently dropped
                        self.replay.append((self.sent, it))
                        self.sent += 1
                        wire.append(await self._encode(it))
                except asyncio.CancelledError:
                    raise
                except ConnectionLost:
                    return
                except Exception as e:  # noqa: BLE001 — error transport
                    # encode infrastructure failed (not the user stream):
                    # the consumer must not hang on a silent pump death —
                    # surface it as the stream's error frame
                    self.error_payload = \
                        self.backend.serde.serialize(e).to_bytes()
                    self.source_done = True
                    await self._push([(_KIND_ERR, self.error_payload)],
                                     done=True)
                    return
                if done:
                    self.source_done = True
                await self._push(wire, done)
                if done:
                    return
        except ConnectionLost:
            # consumer connection died mid-push: keep replay for the
            # pull fallback's resume_pull
            return

    async def _push(self, wire: List[Tuple], done: bool) -> None:
        seq0 = self.sent - len(wire)
        n = await self.conn.push(self.channel_id, seq0, wire, done)
        try:
            frames_total().inc(1.0, {"transport": "push"})
            bytes_total().inc(float(n), {"transport": "push"})
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    async def _encode(self, item: Any) -> Tuple:
        """Inline small values; spill large byte payloads to plasma so
        same-node consumers mmap them instead of copying through the
        frame (the object-plane fast path)."""
        size = _payload_size(item)
        if size is None or size <= inline_max_bytes():
            return (_KIND_VAL, item)
        backend = self.backend
        payload = backend.serde.serialize(item).to_bytes()
        oid = global_worker().next_put_id()
        backend.plasma.write_whole(oid, payload)
        await backend._raylet.call(
            "seal_object", {"oid": oid.hex(), "size": len(payload)})
        ref = ObjectRef(oid, owner=backend.address)
        return (_KIND_OID, ref._descriptor(), len(payload))


def _payload_size(item: Any) -> Optional[int]:
    """Cheap size probe for spill decisions: byte-likes and array-likes
    report their payload size; small scalars/objects return None (inline,
    no serialization probe on the per-token hot path)."""
    if isinstance(item, (bytes, bytearray, memoryview)):
        return len(item)
    if isinstance(item, str):
        return len(item)
    nbytes = getattr(item, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    return None


async def handle_subscribe(backend, p: Dict) -> Dict:
    """``stream_subscribe`` RPC handler (registered by ClusterBackend on
    every process): bind the registered source ``sid`` to a push endpoint
    on the connection this RPC arrived on."""
    sid = p.get("sid")
    channel_id = p.get("channel")
    conn = current_server_connection()
    if conn is None or not conn.alive:
        return {"ok": False, "error": "no connection context"}
    with _reg_lock:
        rs = _sources.get(sid)
    if rs is None:
        return {"ok": False, "unknown": True}
    if rs.binding is not None:
        return {"ok": False, "busy": True}
    binding = _PushBinding(backend, rs, conn, channel_id,
                           int(p.get("window") or stream_window()))
    rs.binding = binding
    conn.endpoints[channel_id] = binding
    binding.start()
    return {"ok": True}


# ---------------------------------------------------------------------------
# consumer side
# ---------------------------------------------------------------------------


async def subscribe(backend, address: str, sid: str,
                    window: Optional[int] = None) -> Optional[StreamChannel]:
    """Open a channel to the producer at ``address`` and subscribe it to
    stream ``sid``. Returns None when the producer doesn't serve push
    (unknown sid / already bound) — the caller stays on the pull path."""
    win = window or stream_window()
    client = await backend._pool.get(address)
    ch = client.open_channel(win)
    try:
        reply = await client.call(
            "stream_subscribe",
            {"sid": sid, "channel": ch.id, "window": win}, timeout=30.0)
    except Exception:
        client._channels.pop(ch.id, None)
        raise
    if not reply.get("ok"):
        client._channels.pop(ch.id, None)
        return None
    return ch


async def take_decoded(backend, ch: StreamChannel) -> Tuple[Any, bool]:
    """Next decoded item from a push channel: ``(item, False)`` or
    ``(None, True)`` at end of stream. Raises ChannelBroken on transport
    loss (the consumer falls back to pull) and re-raises a pushed error
    frame (stream failure transport, matching the pull path's
    next_chunks contract)."""
    c = _chaos._STATE
    if c is not None:
        f = _chaos.maybe_fire("rpc.drop", target="stream_push")
        if f is not None:
            raise ChannelBroken("chaos: dropped push stream")
    item = await ch.take()
    if item is CHANNEL_DONE:
        return (None, True)
    return await take_decoded_wire(backend, item)


async def take_decoded_wire(backend, wire_item: Tuple) -> Tuple[Any, bool]:
    """Decode one raw frame item: inline values pass through, oid frames
    resolve through the object plane (same-node: zero-copy mmap), error
    frames re-raise the stream's failure."""
    kind = wire_item[0]
    if kind == _KIND_VAL:
        return (wire_item[1], False)
    if kind == _KIND_OID:
        ref = ObjectRef._rehydrate(wire_item[1])
        payload = await backend._resolve_payload(ref, timeout=60.0)
        return (backend.serde.deserialize_payload(payload), False)
    if kind == _KIND_ERR:
        err = backend.serde.deserialize_payload(memoryview(wire_item[1]))
        if isinstance(err, BaseException):
            raise err
        raise RuntimeError(f"stream failed: {err!r}")
    raise RuntimeError(f"unknown stream frame kind {kind!r}")


def inline_values(wire_items: List[Tuple]) -> Tuple[List[Any], List[Tuple]]:
    """(decoded inline-value prefix, undecoded remainder): the proxy's
    zero-await burst path takes the prefix; oid/error frames wait for
    the async decoding path."""
    out: List[Any] = []
    for i, w in enumerate(wire_items):
        if w[0] != _KIND_VAL:
            return out, list(wire_items[i:])
        out.append(w[1])
    return out, []


async def decode_backlog(backend, ch: Optional[StreamChannel],
                         wire: List[Tuple]) -> Tuple[List[Any], bool]:
    """Fallback prologue: decode every frame the consumer physically
    possesses (parked wire items + the channel's remaining buffer) so the
    resume point is exact. Error frames are SKIPPED — the producer's
    binding holds the error and redelivers it through ``reclaim``."""
    if ch is not None:
        wire = list(wire) + ch.take_available()
    out: List[Any] = []
    saw_error = False
    for w in wire:
        if w[0] == _KIND_ERR:
            saw_error = True
            continue
        item, _ = await take_decoded_wire(backend, w)
        out.append(item)
    done = (not saw_error) and ch is not None and ch.is_done()
    return (out, done)
