"""Worker process entry: task execution loop + actor hosting.

Reference analog: ``python/ray/_private/workers/default_worker.py`` plus the
execution side of the core worker (``execute_task`` in ``_raylet.pyx:1444``,
``CoreWorkerDirectTaskReceiver`` and the actor scheduling queues). A worker:
  - builds its own ClusterBackend so user code can call ``ray_tpu.*``;
  - serves ``push_task`` (normal tasks, one at a time — the raylet gates
    concurrency by resources);
  - serves ``create_actor``/``actor_call`` with arrival-ordered execution and
    ``max_concurrency`` consumers (sync methods on threads, async methods as
    coroutines — the reference's three queue flavors);
  - exits when its raylet connection drops.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import sys
import threading
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu.cluster import stream as rt_stream
from ray_tpu.cluster.rpc import RpcClient
from ray_tpu.cluster.worker_core import ClusterBackend
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.exceptions import TaskError
from ray_tpu.util import chaos as C


class _GenStreamPump:
    """Producer pump for the streaming-generator push path: the task
    executor thread feeds ``(index, payload|None)`` items, the
    cluster/stream.py push binding drains them on the io loop (the
    ``async take`` pump protocol). Bounded so the generator cannot run
    arbitrarily far ahead of the credit window."""

    def __init__(self, loop, maxsize: int):
        self._loop = loop
        self._cond = threading.Condition()
        self._items: deque = deque()  # rt: guarded-by(_cond)
        self._done = False  # rt: guarded-by(_cond)
        self._stopped = False  # rt: guarded-by(_cond)
        self._maxsize = max(1, maxsize)
        self._avail = asyncio.Event()  # loop-affine

    # -- task thread side --------------------------------------------------
    def feed(self, item: Tuple) -> bool:
        """Block while full; False once the binding detached (broken
        channel / consumer stop) — the caller reverts to the acked path."""
        with self._cond:
            while len(self._items) >= self._maxsize and not self._stopped:
                self._cond.wait(0.2)
            if self._stopped:
                return False
            self._items.append(item)
        self._wake()
        return True

    def feed_done(self) -> None:
        with self._cond:
            self._done = True
        self._wake()

    def drain_unsent(self) -> List[Tuple]:
        """Items fed but never taken by the binding (fallback prologue)."""
        with self._cond:
            out = list(self._items)
            self._items.clear()
            return out

    @property
    def stopped(self) -> bool:
        with self._cond:
            return self._stopped

    # -- binding side ------------------------------------------------------
    def binding_stopped(self) -> None:
        """Called by the push binding when it detaches (any thread)."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._wake()

    def close(self) -> None:
        self.binding_stopped()

    def _wake(self) -> None:
        try:
            self._loop.call_soon_threadsafe(self._avail.set)
        except RuntimeError:
            pass  # loop closed at teardown

    async def take(self, max_items: int) -> Tuple[List[Any], bool]:
        while True:
            with self._cond:
                if self._items:
                    out = []
                    while self._items and len(out) < max_items:
                        out.append(self._items.popleft())
                    done = self._done and not self._items
                    self._cond.notify_all()
                    return out, done
                if self._done:
                    return [], True
                if self._stopped:
                    # binding is detaching: hand control back so its
                    # pump loop can observe _stop and finish
                    return [], False
                self._avail.clear()
            await self._avail.wait()


class _GenStreamPusher:
    """Push-transport driver for one streaming-generator task: registers
    the source, announces it to the owner (``stream_begin`` — the owner
    subscribes back over its pooled connection), and feeds items through
    the pump. On ANY detachment the worker resends the unacked tail over
    the legacy acked ``stream_item`` path — redelivery is idempotent
    (the owner stores items by index), so the stream is token-exact
    through the fallback."""

    def __init__(self, backend, task_id_hex: str, owner: str):
        self.backend = backend
        self.sid = f"g:{task_id_hex}"
        self.task_id_hex = task_id_hex
        self.owner = owner
        self.pump: Optional[_GenStreamPump] = None

    def begin(self) -> bool:
        # provisional pump: resized to the owner's window on acceptance
        self.pump = _GenStreamPump(self.backend.loop,
                                   rt_stream.stream_window() // 4)
        rt_stream.register_source(self.sid, self.pump)
        try:
            reply = self.backend.io.run(self._announce(), timeout=30.0)
        except Exception:  # noqa: BLE001 — owner unreachable: acked path
            reply = None
        if not (reply and reply.get("push")):
            rt_stream.unregister_source(self.sid)
            return False
        # producer-side lag bound: pump buffer rides ON TOP of the
        # credit window, so keep it a fraction of it
        self.pump._maxsize = max(1, int(reply.get("window") or 16) // 4)
        return True

    async def _announce(self):
        client = await self.backend._pool.get(self.owner)
        return await client.call(
            "stream_begin",
            {"task_id": self.task_id_hex, "sid": self.sid,
             "address": self.backend.server.address})

    @property
    def active(self) -> bool:
        return self.pump is not None and not self.pump.stopped

    def feed(self, index: int, payload: Optional[bytes]) -> bool:
        return self.pump.feed((index, payload))

    def settle(self, finish: bool) -> Optional[List[Tuple]]:
        """Settle the push stream: ``finish=True`` feeds the done marker
        first (generator exhausted/raised). Returns None when the stream
        completed over push (every item acked), else the (index,
        payload) tail to redeliver over the acked path — pushed-but-
        unacked replay plus anything still parked in the pump."""
        if finish:
            self.pump.feed_done()
        else:
            self.pump.binding_stopped()
        try:
            tail = self.backend.io.run(
                rt_stream.settle_source(self.sid), timeout=90.0)
        except Exception:  # noqa: BLE001 — loop wedged: we cannot learn
            # what was acked, so resend everything still replayable (racy
            # off-loop snapshot; over-delivery is idempotent by index,
            # dropping pushed-but-unacked items would hole the stream)
            tail = rt_stream.peek_unacked(self.sid)
            rt_stream.unregister_source(self.sid)
        if tail is None:
            return None
        pending = {idx: pl for idx, pl in tail}
        for idx, pl in self.pump.drain_unsent():
            pending[idx] = pl
        return sorted(pending.items())


class WorkerProcess:
    def __init__(self):
        self.worker_id = os.environ["RT_WORKER_ID"]
        self.backend = ClusterBackend(
            gcs_address=os.environ["RT_GCS_ADDR"],
            raylet_address=os.environ["RT_RAYLET_ADDR"],
            node_id=os.environ["RT_NODE_ID"],
            session_name=os.environ["RT_SESSION_NAME"],
            job_id=JobID.from_int(0),
            role="worker")
        self._task_pool = ThreadPoolExecutor(max_workers=1,
                                             thread_name_prefix="rt-exec")
        # Actor state
        self._actor_instance: Any = None
        self._actor_id: Optional[str] = None
        self._actor_queues: Dict[str, asyncio.Queue] = {}
        self._actor_threads: Optional[ThreadPoolExecutor] = None
        # client-side failure-emission rate limit (see _failure_event)
        from ray_tpu.core.failure import EmitLimiter

        self._failure_limiter = EmitLimiter(cap=256)

    def start(self) -> None:
        from ray_tpu.core.worker import global_worker

        self.backend.connect()
        self._materialize_runtime_env()
        srv = self.backend.server
        srv.register("push_task", self.rpc_push_task)
        srv.register("create_actor", self.rpc_create_actor)
        srv.register("actor_call", self.rpc_actor_call)
        srv.register("exit", self.rpc_exit)
        srv.register("dump_stacks", self.rpc_dump_stacks)
        srv.register("chaos_arm", self.rpc_chaos_arm)
        global_worker().connect(self.backend, self.backend.job_id, "worker")
        self.backend.io.run(self.backend._raylet.call("worker_ready", {
            "worker_id": self.worker_id,
            "address": self.backend.server.address}))
        # Exit when the raylet goes away.
        self.backend.io.spawn(self._watch_raylet())

    def _materialize_runtime_env(self) -> None:
        """Make the assigned runtime env live BEFORE user code can run
        (reference: the runtime-env agent prepares, ``context.py`` applies;
        here the keyed-by-env worker does both at startup)."""
        wire_json = os.environ.get("RT_RUNTIME_ENV_JSON")
        if not wire_json:
            return
        import json

        from ray_tpu.runtime_env import materialize

        wire = json.loads(wire_json)
        cache_root = os.path.join(get_config().session_dir_root,
                                  os.environ["RT_SESSION_NAME"],
                                  "runtime_env")
        materialize(wire, self.backend.kv_get, cache_root)

    async def _watch_raylet(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            if self.backend._raylet._closed:
                os._exit(0)
            # buffered rpc.* chaos fires ship from the watch loop (the
            # rpc layer itself has no GCS handle)
            self.backend._drain_chaos_events()

    async def rpc_chaos_arm(self, p):
        """Live (re)arming from this worker's raylet when `rt chaos` ships
        a new plan revision (new workers arm from RT_CHAOS_PLAN_JSON)."""
        try:
            if p.get("plan"):
                C.arm(p["plan"], rev=p.get("rev", 0))
            else:
                C.disarm()
            return {"ok": True}
        except (ValueError, TypeError) as e:
            return {"ok": False, "error": str(e)}

    def _chaos_kill_payload(self, target, task_id, fault):
        return C.event_payload(
            "worker.kill", fault, node_id=os.environ.get("RT_NODE_ID"),
            worker_id=self.worker_id, task_id=task_id, name=target)

    def _maybe_chaos_kill(self, target: Optional[str],
                          task_id: Optional[str]) -> None:
        """worker.kill injection site (task-executor thread): die
        mid-execution like a real crash (``os._exit(137)``), after
        synchronously stamping the chaos-origin event (a fire-and-forget
        would die with the process)."""
        f = C.maybe_fire("worker.kill", target=target)
        if f is None:
            return
        try:
            self.backend.io.run(self.backend._gcs.call(
                "failure_event",
                self._chaos_kill_payload(target, task_id, f)), timeout=5.0)
        except Exception:  # noqa: BLE001 — the kill still happens
            pass
        os._exit(137)

    async def _maybe_chaos_kill_async(self, target: Optional[str],
                                      task_id: Optional[str]) -> None:
        """Event-loop twin of :meth:`_maybe_chaos_kill` (actor methods run
        their dispatch on the io loop, where a blocking io.run would
        deadlock)."""
        f = C.maybe_fire("worker.kill", target=target)
        if f is None:
            return
        try:
            await asyncio.wait_for(self.backend._gcs.call(
                "failure_event",
                self._chaos_kill_payload(target, task_id, f)), 5.0)
        except Exception:  # noqa: BLE001 — the kill still happens
            pass
        os._exit(137)

    async def rpc_exit(self, p):
        asyncio.get_running_loop().call_later(0.1, os._exit, 0)
        return {"ok": True}

    async def rpc_dump_stacks(self, p):
        """Live stack snapshot of every thread (the py-spy-equivalent
        surface; see ``util/profiling.py``). Runs on the event loop — it
        responds even while user tasks block executor threads."""
        from ray_tpu.util.profiling import format_current_stacks

        return {"pid": os.getpid(), "stacks": format_current_stacks()}

    def _failure_event(self, message: str, **fields) -> None:
        """Stamp a task-error FailureEvent on the GCS feed (the executing
        worker is the only process that always sees a user exception — the
        caller may never ``get`` the ref). Rate-limited per failing
        function/method via the shared EmitLimiter: a map over bad input
        failing thousands of tasks per second must not stream one GCS RPC
        per execution (the GCS dedups rows, not RPCs)."""
        from ray_tpu.core import failure as F

        if not self._failure_limiter.allow(fields.get("name") or message):
            return
        F.emit(self.backend.io.spawn, self.backend._gcs, F.TASK_ERROR,
               message, node_id=os.environ.get("RT_NODE_ID"),
               worker_id=self.worker_id, **fields)

    # ---- argument / return marshalling -------------------------------------
    def _resolve_args(self, wire_args: List[Tuple], wire_kwargs: Dict[str, Tuple]):
        """Deserialize inline values and fetch refs (dependency resolution)."""
        refs: List[ObjectRef] = []
        slots: List[Tuple[str, Any]] = []

        def scan(item):
            kind, data = item
            if kind == "ref":
                ref = ObjectRef._rehydrate(data)
                refs.append(ref)
                return ("ref", len(refs) - 1)
            return ("val", self.backend.serde.deserialize_payload(memoryview(data)))

        arg_slots = [scan(a) for a in wire_args]
        kwarg_slots = {k: scan(v) for k, v in wire_kwargs.items()}
        values = self.backend.get(refs, timeout=None) if refs else []

        def fill(slot):
            kind, v = slot
            return values[v] if kind == "ref" else v

        return [fill(s) for s in arg_slots], {k: fill(s) for k, s in kwarg_slots.items()}

    def _pack_returns(self, result: Any, task_id: TaskID, num_returns: int):
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"expected {num_returns} return values, got {len(values)}")
        out = []
        small_limit = get_config().max_direct_call_object_size
        for i, v in enumerate(values):
            payload = self.backend.serde.serialize(v).to_bytes()
            if len(payload) > small_limit:
                oid = ObjectID.for_return(task_id, i)
                self.backend.plasma.write_whole(oid, payload)
                self.backend.io.run(self.backend._raylet.call(
                    "seal_object", {"oid": oid.hex(), "size": len(payload)}))
                out.append(("plasma", len(payload)))
            else:
                out.append(("val", payload))
        return out

    def _error_returns(self, err: BaseException, num_returns: int):
        payload = self.backend.serde.serialize(err).to_bytes()
        return [("val", payload)] * num_returns

    # ---- normal tasks -------------------------------------------------------
    async def rpc_push_task(self, p):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self._task_pool,
                                          self._execute_task_sync, p)

    def _execute_task_sync(self, p) -> Dict:
        import time as _time

        from ray_tpu.core.worker import global_worker

        self._maybe_chaos_kill(p.get("fn_name"), p.get("task_id"))
        task_id = TaskID.from_hex(p["task_id"])
        self.backend.job_id = JobID.from_hex(p["job_id"])
        worker = global_worker()
        worker.job_id = self.backend.job_id
        token = worker.enter_task_context(task_id)
        self.backend._current_task_id = p["task_id"]
        streaming = p["num_returns"] == "streaming"
        from ray_tpu.util import tracing

        traced = p.get("trace") is not None  # phase stamps ride the span
        trace_token = tracing.activate(p.get("trace"))
        t0 = t1 = t2 = 0.0

        def _failure_phases() -> Dict[str, float]:
            # best-effort phases for a raised task: whatever stamps exist
            # (a missing breakdown would make the raylet book the whole
            # execution as "transfer" and misdirect the investigation)
            now = _time.perf_counter()
            wp = {"arg_fetch": (t1 or now) - t0}
            if t1:
                wp["execute"] = (t2 or now) - t1
            return wp

        try:
            t0 = _time.perf_counter() if traced else 0.0
            fn = self.backend.load_function(p["fn_id"])
            args, kwargs = self._resolve_args(p["args"], p["kwargs"])
            t1 = _time.perf_counter() if traced else 0.0
            result = fn(*args, **kwargs)
            t2 = _time.perf_counter() if traced else 0.0
            if streaming:
                reply = self._stream_results(result, task_id, p)
                if traced:
                    # execute covers driving the generator (production +
                    # per-item pushes); items store as they stream, so
                    # there is no separate result_store phase
                    reply["worker_phases"] = {
                        "arg_fetch": t1 - t0,
                        "execute": _time.perf_counter() - t2}
                return reply
            returns = self._pack_returns(result, task_id, p["num_returns"])
            reply = {"returns": returns}
            if traced:
                reply["worker_phases"] = {
                    "arg_fetch": t1 - t0, "execute": t2 - t1,
                    "result_store": _time.perf_counter() - t2}
            return reply
        except TaskError as e:
            # a TaskError here is PROPAGATION (a dependency's failure
            # re-raised while fetching args / inside user code) — its
            # origin worker already emitted the task_error event; emitting
            # again would attribute one upstream error to every
            # downstream consumer
            if streaming:
                reply = {"streaming_done": 0,
                         "stream_error": self.backend.serde.serialize(e).to_bytes()}
            else:
                reply = {"returns": self._error_returns(e, p["num_returns"])}
            if traced:
                reply["worker_phases"] = _failure_phases()
            return reply
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            self._failure_event(f"{type(e).__name__}: {e}",
                                task_id=p["task_id"], name=p["fn_name"])
            err = TaskError(p["fn_name"], e)
            if streaming:
                reply = {"streaming_done": 0,
                         "stream_error": self.backend.serde.serialize(err).to_bytes()}
            else:
                reply = {"returns": self._error_returns(err, p["num_returns"])}
            if traced:
                reply["worker_phases"] = _failure_phases()
            return reply
        finally:
            tracing.deactivate(trace_token)
            self.backend._current_task_id = None
            worker.exit_task_context(token)

    def _stream_results(self, result, task_id: TaskID, p) -> Dict:
        """Drive a generator task. Default transport is PUSH
        (cluster/stream.py, PR 11's named unclaimed stretch): one
        ``stream_begin`` handshake binds the owner to this worker's
        stream source, then every item rides a one-way credit-windowed
        frame — O(1) RPCs per stream instead of one acked ``stream_item``
        RPC per item. The acked per-item path (reference: item reporting
        ``_raylet.pyx:1090``) remains: primary when push is off / the
        owner declines (tiny ``_stream_max_buffer`` bounds want per-item
        acks), and the FALLBACK when a push channel breaks — the unacked
        tail is redelivered through it by index, so the stream stays
        token-exact across the switch. Small items ride the frame/RPC;
        large go to plasma with only the index notification inline."""
        it = iter(result)
        small_limit = get_config().max_direct_call_object_size
        owner = p["owner"]
        pusher: Optional[_GenStreamPusher] = None
        if rt_stream.push_enabled():
            pusher = _GenStreamPusher(self.backend, p["task_id"], owner)
            if not pusher.begin():
                pusher = None

        async def _send(msg):
            client = await self.backend._pool.get(owner)
            return await client.call("stream_item", msg)

        def _legacy_send(index: int, payload: Optional[bytes]) -> Dict:
            msg = {"task_id": p["task_id"], "index": index}
            if payload is not None:
                msg["payload"] = payload
            return self.backend.io.run(_send(msg))

        def _settle_push(finish: bool) -> bool:
            """Settle/fall back; returns False when the owner is gone."""
            nonlocal pusher
            tail = pusher.settle(finish)
            pusher = None
            for idx, pl in tail or ():
                if _legacy_send(idx, pl).get("gone"):
                    return False
            return True

        i = 0
        while True:
            try:
                v = next(it)
            except StopIteration:
                if pusher is not None:
                    _settle_push(finish=True)
                return {"streaming_done": i}
            # rt: lint-allow(except-discipline) error transport: the
            # user generator's failure ships to the owner as stream_error
            except BaseException as e:  # noqa: BLE001
                traceback.print_exc()
                if not isinstance(e, TaskError):  # origin only
                    self._failure_event(
                        f"{type(e).__name__}: {e}", task_id=p["task_id"],
                        name=p["fn_name"])
                err = TaskError(p["fn_name"], e)
                if pusher is not None:
                    # the error lands at index `produced` on the owner:
                    # every pushed item must be delivered BEFORE the
                    # reply carries the error, or it would overwrite a
                    # lost item's slot
                    _settle_push(finish=True)
                return {"streaming_done": i,
                        "stream_error": self.backend.serde.serialize(err).to_bytes()}
            payload = self.backend.serde.serialize(v).to_bytes()
            inline: Optional[bytes] = None
            if len(payload) > small_limit:
                oid = ObjectID.for_return(task_id, i)
                self.backend.plasma.write_whole(oid, payload)
                self.backend.io.run(self.backend._raylet.call(
                    "seal_object", {"oid": oid.hex(), "size": len(payload)}))
            else:
                inline = payload
            if pusher is not None:
                if pusher.feed(i, inline):
                    i += 1
                    continue
                # binding detached (broken channel / consumer stop):
                # redeliver the unacked tail and continue on acks
                if not _settle_push(finish=False):
                    return {"streaming_done": i}
            ack = _legacy_send(i, inline)
            if ack.get("gone"):
                return {"streaming_done": i}  # consumer went away: stop
            i += 1

    # ---- actors -------------------------------------------------------------
    async def rpc_create_actor(self, p):
        spec = p["spec"]
        loop = asyncio.get_running_loop()
        self._actor_id = spec["actor_id"]
        max_conc = spec.get("max_concurrency", 1)
        # Concurrency groups (reference: ConcurrencyGroupManager,
        # ``concurrency_group_manager.h``): each named group gets its own
        # arrival-ordered queue + consumer pool, so a saturated "compute"
        # group can't starve "io" methods. The default group runs
        # max_concurrency consumers; method->group routing is read off the
        # loaded class (``@ray_tpu.method(concurrency_group=...)``).
        groups = dict(spec.get("concurrency_groups") or {})
        for name, n in groups.items():
            if not isinstance(n, int) or n < 1:
                return {"ok": False,
                        "error": f"concurrency_groups[{name!r}] must be a "
                                 f"positive int, got {n!r}"}
        # "_default" may be user-sized (the documented spelling for sizing
        # the default pool); otherwise it runs max_concurrency consumers
        groups.setdefault("_default", max_conc)
        self._actor_queues = {g: asyncio.Queue() for g in groups}
        self._method_groups: Dict[str, str] = {}
        total_threads = sum(groups.values())
        self._actor_threads = ThreadPoolExecutor(
            max_workers=max(1, total_threads), thread_name_prefix="rt-actor")
        from ray_tpu.cluster.rpc import spawn_task

        # strong refs: a GC'd consumer would strand queued calls forever
        self._consumer_tasks = [
            spawn_task(self._actor_consumer(self._actor_queues[g]))
            for g, n in groups.items() for _ in range(n)]

        def build():
            from ray_tpu.core.worker import global_worker

            self.backend.job_id = JobID.from_hex(spec["job_id"])
            global_worker().job_id = self.backend.job_id
            cls = self.backend.load_function(spec["class_id"])
            args, kwargs = self._resolve_args(spec["args"], spec["kwargs"])
            return cls(*args, **kwargs)

        try:
            self._actor_instance = await loop.run_in_executor(
                self._actor_threads, build)
            return {"ok": True, "address": self.backend.server.address}
        # rt: lint-allow(except-discipline) error transport: __init__
        # failure crosses the wire as the create-actor reply
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            return {"ok": False, "error": f"__init__ failed: {e!r}"}

    async def _actor_consumer(self, q: asyncio.Queue) -> None:
        while True:
            coro, fut = await q.get()
            try:
                result = await coro
                if not fut.done():
                    fut.set_result(result)
            except asyncio.CancelledError:
                # teardown cancelling the consumer mid-method: fail the
                # waiter, then RE-RAISE — swallowing would leave this loop
                # immortal with cancellation recorded as a method error
                if not fut.done():
                    fut.cancel()
                raise
            except BaseException as e:  # noqa: BLE001
                if not fut.done():
                    fut.set_exception(e)

    def _queue_for(self, method_name: str) -> asyncio.Queue:
        group = self._method_groups.get(method_name)
        if group is None:
            fn = getattr(type(self._actor_instance), method_name, None)
            group = getattr(fn, "_concurrency_group", "_default")
            if group not in self._actor_queues:
                # loud: a typo'd group would silently lose the isolation
                # the user configured (reference errors at submission too)
                raise ValueError(
                    f"method {method_name!r} names concurrency group "
                    f"{group!r}, but the actor declared "
                    f"{sorted(g for g in self._actor_queues if g != '_default')}")
            self._method_groups[method_name] = group
        return self._actor_queues[group]

    async def rpc_actor_call(self, p):
        import time as _time

        loop = asyncio.get_running_loop()
        if p.get("trace") is not None:  # phase tracing: queue-wait stamp
            p["_t_enq"] = _time.perf_counter()
        fut = loop.create_future()
        await self._queue_for(p["method"]).put(
            (self._run_actor_method(p), fut))
        return await fut

    async def _run_actor_method(self, p) -> Dict:
        loop = asyncio.get_running_loop()
        task_id = TaskID.from_hex(p["task_id"])
        method_name = p["method"]
        await self._maybe_chaos_kill_async(method_name, p.get("task_id"))
        method = getattr(self._actor_instance, method_name, None)
        if method is None:
            err = TaskError(method_name, AttributeError(
                f"actor has no method {method_name!r}"))
            return {"returns": self._error_returns(err, p["num_returns"])}
        if inspect.iscoroutinefunction(method):
            import time as _time

            from ray_tpu.util import tracing

            traced = p.get("trace") is not None
            trace_token = tracing.activate(p.get("trace"))
            if traced:
                self._emit_span_event(p, "RUNNING")
            try:
                t0 = _time.perf_counter() if traced else 0.0
                args, kwargs = await loop.run_in_executor(
                    self._actor_threads, self._resolve_args, p["args"], p["kwargs"])
                t1 = _time.perf_counter() if traced else 0.0
                result = await method(*args, **kwargs)
                t2 = _time.perf_counter() if traced else 0.0
                returns = await loop.run_in_executor(
                    self._actor_threads, self._pack_returns, result, task_id,
                    p["num_returns"])
                reply = {"returns": returns}
                if traced:
                    reply["worker_phases"] = self._actor_phases(
                        p, t0, t1, t2, _time.perf_counter())
                    self._emit_span_event(p, "FINISHED",
                                          phases=reply["worker_phases"])
                return reply
            # rt: lint-allow(except-discipline) error transport: the
            # reply IS the unwind path — re-raising would strand the
            # owner's future until connection loss
            except BaseException as e:  # noqa: BLE001
                if traced:
                    self._emit_span_event(p, "FAILED")
                if not isinstance(e, TaskError):  # origin only, not
                    self._failure_event(          # propagated upstream errors
                        f"{type(e).__name__}: {e}", task_id=p["task_id"],
                        actor_id=p.get("actor_id"), name=method_name)
                return {"returns": self._error_returns(
                    TaskError(method_name, e), p["num_returns"])}
            finally:
                tracing.deactivate(trace_token)
        return await loop.run_in_executor(
            self._actor_threads, self._execute_actor_method_sync, p, method, task_id)

    def _execute_actor_method_sync(self, p, method, task_id: TaskID) -> Dict:
        import time as _time

        from ray_tpu.core.worker import global_worker

        from ray_tpu.util import tracing

        worker = global_worker()
        token = worker.enter_task_context(
            task_id, ActorID.from_hex(p["actor_id"]))
        traced = p.get("trace") is not None
        trace_token = tracing.activate(p.get("trace"))
        if traced:
            self._emit_span_event(p, "RUNNING")
        try:
            t0 = _time.perf_counter() if traced else 0.0
            args, kwargs = self._resolve_args(p["args"], p["kwargs"])
            t1 = _time.perf_counter() if traced else 0.0
            result = method(*args, **kwargs)
            t2 = _time.perf_counter() if traced else 0.0
            reply = {"returns": self._pack_returns(result, task_id,
                                                   p["num_returns"])}
            if traced:
                reply["worker_phases"] = self._actor_phases(
                    p, t0, t1, t2, _time.perf_counter())
                self._emit_span_event(p, "FINISHED",
                                      phases=reply["worker_phases"])
            return reply
        except BaseException as e:  # noqa: BLE001
            traceback.print_exc()
            if traced:
                self._emit_span_event(p, "FAILED")
            if not isinstance(e, TaskError):  # origin only, not
                self._failure_event(          # propagated upstream errors
                    f"{type(e).__name__}: {e}", task_id=p["task_id"],
                    actor_id=p.get("actor_id"), name=p["method"])
            return {"returns": self._error_returns(
                TaskError(p["method"], e), p["num_returns"])}
        finally:
            tracing.deactivate(trace_token)
            worker.exit_task_context(token)

    @staticmethod
    def _actor_phases(p, t0: float, t1: float, t2: float,
                      t3: float) -> Dict[str, float]:
        """Actor-call phase partition: actor calls bypass the raylet, so
        queue_wait here is the actor's own concurrency-group queue (stamped
        at rpc_actor_call enqueue)."""
        phases = {"arg_fetch": t1 - t0, "execute": t2 - t1,
                  "result_store": t3 - t2}
        t_enq = p.get("_t_enq")
        if t_enq is not None:
            phases["queue_wait"] = max(0.0, t0 - t_enq)
        return phases

    def _emit_span_event(self, p, state: str,
                         phases: Optional[Dict] = None) -> None:
        """Actor-call spans: actor calls bypass the raylet (direct
        worker->worker), so the executing worker reports the task event the
        raylet would have (tracing + timeline coverage for actor methods);
        ``phases`` carries the per-phase breakdown on FINISHED."""
        async def _send():
            try:
                msg = {
                    "task_id": p["task_id"],
                    "name": f"{type(self._actor_instance).__name__}."
                            f"{p['method']}",
                    "state": state, "node_id": os.environ["RT_NODE_ID"],
                    "trace": p.get("trace")}
                if phases:
                    msg["phases"] = phases
                await self.backend._gcs.call("task_event", msg)
            except Exception:
                pass

        self.backend.io.spawn(_send())


def main() -> None:
    # Debuggability: `kill -USR1 <worker_pid>` dumps all thread stacks to the
    # worker's log (stderr) — the only way to see inside a wedged worker.
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, file=sys.stderr, all_threads=True)
    # TPU perf flags (latency-hiding scheduler, async collectives) must be
    # in the env before this process's first jax/libtpu init; workers are
    # where jitted training steps actually run. No-op on CPU backends.
    from ray_tpu.parallel.xla_flags import apply_tpu_perf_flags

    apply_tpu_perf_flags()
    wp = WorkerProcess()
    wp.start()
    threading.Event().wait()  # io loop thread does the work


if __name__ == "__main__":
    main()
