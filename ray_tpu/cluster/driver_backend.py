"""Cluster bring-up and the driver's backend.

Reference analog: ``python/ray/_private/node.py`` + ``services.py`` — the
process-tree orchestration behind ``ray.init()``. Redesign: the GCS and
raylets are asyncio components, so a "node" is a component on an event loop
rather than a forced OS process; the default ``init()`` hosts the GCS + head
raylet on the driver's background io thread and spawns real worker
subprocesses. ``cluster_utils.Cluster`` adds more (fake-resource) raylets on
the same loop for multi-node tests — the reference's trick of real control
planes with fake resource counts (SURVEY.md §4), with identical RPC paths to
a true multi-host deployment.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu._private import accelerator
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import JobID
from ray_tpu.cluster.gcs import GcsServer
from ray_tpu.cluster.raylet import Raylet
from ray_tpu.cluster.rpc import EventLoopThread, RpcServer
from ray_tpu.core import resources as res


class ClusterHandle:
    """Owns the in-process control-plane components (GCS + raylets)."""

    def __init__(self, session_name: Optional[str] = None):
        self.session_name = session_name or f"session_{uuid.uuid4().hex[:12]}"
        self.io = EventLoopThread(name="rt-cluster-io")
        self.gcs: Optional[GcsServer] = None
        self.gcs_address: Optional[str] = None
        self.raylets: List[Raylet] = []
        self._gcs_persist_path: Optional[str] = None

    def start_gcs(self, persist_path: Optional[str] = None) -> str:
        self._gcs_persist_path = persist_path

        async def _go():
            self.gcs = GcsServer(persist_path=persist_path)
            server = RpcServer(self.io.loop)
            server.register_object(self.gcs)
            await server.start()
            self.gcs.start_monitor()
            self._gcs_rpc_server = server
            return server.address

        self.gcs_address = self.io.run(_go())
        return self.gcs_address

    def kill_gcs(self) -> None:
        """Chaos helper: take the head down (RPC server closed, component
        stopped). Clients see ConnectionLost; WAL-backed state survives."""
        async def _go():
            await self._gcs_rpc_server.stop()
            await self.gcs.stop()

        self.io.run(_go())
        self.gcs = None

    def restart_gcs(self) -> str:
        """Bring the head back ON THE SAME ADDRESS with the persisted
        state; live raylets reconnect (RpcClient auto_reconnect) and
        re-register via the heartbeat 'unknown' path."""
        port = int(self.gcs_address.rsplit(":", 1)[1])

        async def _go():
            self.gcs = GcsServer(persist_path=self._gcs_persist_path)
            server = RpcServer(self.io.loop)
            server.register_object(self.gcs)
            await server.start(port=port)
            self.gcs.start_monitor()
            self._gcs_rpc_server = server
            return server.address

        self.gcs_address = self.io.run(_go())
        return self.gcs_address

    def add_node(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Raylet:
        total = {
            res.CPU: num_cpus if num_cpus is not None else (os.cpu_count() or 1),
            res.TPU: num_tpus if num_tpus is not None
            else accelerator.autodetect_num_tpu_chips(),
            res.MEMORY: float(os.sysconf("SC_PAGE_SIZE")
                              * os.sysconf("SC_PHYS_PAGES")),
        }
        total.update(resources or {})
        total = {k: v for k, v in total.items() if v}
        node_labels = dict(accelerator.tpu_node_labels())
        node_labels.update(labels or {})
        node_id = uuid.uuid4().hex

        async def _go():
            raylet = Raylet(node_id, self.session_name, self.gcs_address,
                            total, node_labels, self.io.loop)
            await raylet.start()
            return raylet

        raylet = self.io.run(_go())
        self.raylets.append(raylet)
        return raylet

    def remove_node(self, raylet: Raylet) -> None:
        async def _go():
            await self.gcs._mark_node_dead(
                self.gcs.nodes[raylet.node_id], "removed")
            await raylet.stop()

        self.io.run(_go())
        self.raylets.remove(raylet)

    def shutdown(self) -> None:
        async def _go():
            for raylet in self.raylets:
                try:
                    await raylet.stop()
                except Exception:
                    pass
            try:
                if self.gcs is not None:
                    await self.gcs.stop()
                await self._gcs_rpc_server.stop()
            except Exception:
                pass

        try:
            self.io.run(_go(), timeout=get_config().graceful_shutdown_timeout_s)
        except Exception:
            pass
        # Session owner: remove the shared shm dir once, after all nodes stop.
        if self.raylets:
            try:
                self.raylets[0].store.destroy()
            except Exception:
                pass
        self.raylets.clear()
        self.io.stop()


def start_or_connect(address: Optional[str], job_id: JobID, *,
                     num_cpus: Optional[float] = None,
                     num_tpus: Optional[float] = None,
                     resources: Optional[Dict[str, float]] = None,
                     namespace: Optional[str] = None):
    from ray_tpu.cluster.worker_core import ClusterBackend

    if address == "auto":
        from ray_tpu.cluster import node_main

        latest = node_main.read_session_latest()
        if latest is None:
            raise ConnectionError(
                "init(address='auto'): no running cluster found "
                "(start one with `rt start --head`)")
        address = latest["gcs_address"]
    if address and address.startswith("rt://"):
        # Ray-Client analog: rt://<gcs-host:port> — attach WITHOUT shared shm
        return connect_existing(address[len("rt://"):], job_id,
                                namespace=namespace, client_mode=True)
    if address is None:
        cluster = ClusterHandle()
        cluster.start_gcs()
        raylet = cluster.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                  resources=resources)
        backend = ClusterBackend(
            gcs_address=cluster.gcs_address,
            raylet_address=raylet.server.address,
            node_id=raylet.node_id,
            session_name=cluster.session_name,
            job_id=job_id, role="driver", namespace=namespace)
        backend.connect()
        backend._cluster_shutdown_hook = cluster.shutdown
        backend._cluster = cluster
        return backend
    return connect_existing(address, job_id, namespace=namespace)


def connect_existing(gcs_address: str, job_id: JobID, *,
                     namespace: Optional[str] = None,
                     client_mode: bool = False):
    """Attach a driver to a running cluster: pick a raylet from the node
    table (head node preferred) and join its session. ``client_mode``
    (the reference's Ray Client): this process shares NO /dev/shm with the
    cluster — large objects travel via the raylet's chunked put/get RPCs,
    so a laptop can drive a remote TPU pod over plain TCP."""
    import asyncio

    from ray_tpu.cluster.rpc import RpcClient
    from ray_tpu.cluster.worker_core import ClusterBackend

    io = EventLoopThread(name="rt-driver-io")

    async def _discover():
        client = RpcClient(gcs_address, peer_id="driver-discover")
        await client.connect()
        deadline = time.monotonic() + get_config().gcs_rpc_timeout_s
        while time.monotonic() < deadline:
            nodes = await client.call("list_nodes", {})
            alive = [n for n in nodes if n["alive"]]
            if alive:
                await client.close()
                return alive[0]
            await asyncio.sleep(0.2)
        raise TimeoutError(f"no alive nodes at GCS {gcs_address}")

    node = io.run(_discover())
    # Session name comes through the raylet's node entry labels if remote;
    # same-host drivers read it from the env set by `rt start` (later round).
    session_name = os.environ.get("RT_SESSION_NAME",
                                  node.get("labels", {}).get("session", ""))
    backend = ClusterBackend(
        gcs_address=gcs_address,
        raylet_address=node["address"],
        node_id=node["node_id"],
        session_name=session_name or "session_shared",
        job_id=job_id, role="client" if client_mode else "driver",
        namespace=namespace, loop_thread=io,
        shared_store=not client_mode)
    backend.connect()
    return backend
