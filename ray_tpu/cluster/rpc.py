"""Asyncio TCP RPC: the framework's gRPC-equivalent plumbing.

Fills the role of the reference's ``src/ray/rpc/`` (``GrpcServer``,
``ClientCall`` with connection pooling): every process runs one ``RpcServer``
on its background event-loop thread; ``RpcClient`` multiplexes concurrent
calls over a single connection with correlation ids. ``SyncRpcProxy`` adapts
the async client for synchronous callers (the driver main thread, task code).

Frame format: 4-byte LE length | pickled (kind, msg_id, method, payload).
Payloads are plain picklable values — large tensors never travel here; they
go through the shm object plane.

Push-based streaming (the per-token-RPC killer, reference: Ray's core
streaming generators pushing results over the worker's persistent
connection instead of the caller polling): two ONE-WAY frame kinds ride
the same connections. A server endpoint pushes ``_PUSH`` frames down an
established connection keyed by channel id — no reply slot, no
correlation future — and the client answers with ``_CREDIT`` frames
carrying its cumulative consumed count, which is the backpressure window:
a producer with ``sent - acked >= window`` parks until credit arrives
instead of ballooning either side's buffers. ``StreamChannel`` is the
client half; ``ServerConnection`` (exposed to handlers via
``current_server_connection()``) is the server half. Connection loss
fails every channel on it — consumers fall back to their pull path.
"""

from __future__ import annotations

import asyncio
import contextvars
import pickle
import socket
import struct
import threading
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct("<I")
_REQ, _REP, _ERR, _PUSH, _CREDIT = 0, 1, 2, 3, 4

MAX_FRAME = 512 * 1024 * 1024


class RpcError(Exception):
    pass


# Strong references for fire-and-forget tasks. The event loop keeps only
# WEAK references to tasks; a pending task whose await chain isn't rooted in
# a live object is garbage-collected MID-FLIGHT ("Task was destroyed but it
# is pending!"). Observed in the wild: a GC'd GcsServer._schedule_actor left
# its actor PENDING_CREATION forever, and GC'd worker-side handler/consumer
# tasks swallowed delivered actor calls without ever replying. Every
# fire-and-forget in the framework must go through spawn_task().
_BG_TASKS: set = set()


def spawn_task(coro) -> "asyncio.Task":
    t = asyncio.ensure_future(coro)
    _BG_TASKS.add(t)
    t.add_done_callback(_BG_TASKS.discard)
    return t


async def cancel_and_wait(*tasks) -> None:
    """Cancel tasks and await their completion, swallowing every outcome
    (CancelledError is a BaseException, hence the explicit tuple)."""
    live = [t for t in tasks if t is not None and not t.done()]
    for t in live:
        t.cancel()
    for t in live:
        try:
            await t
        except asyncio.CancelledError:
            cur = asyncio.current_task()
            # Task.cancelling() is 3.11+; on 3.10 fall back to swallowing
            # (the pre-cancelling() semantics) rather than crashing every
            # teardown path with AttributeError.
            cancelling = getattr(cur, "cancelling", None)
            if cancelling is not None and cancelling():
                raise  # our caller was cancelled at this await — honor it
        except Exception:  # noqa: BLE001
            pass


class ConnectionLost(RpcError):
    pass


class ChannelBroken(RpcError):
    """The connection carrying a push stream died (or the producer
    vanished). Consumers catch this and fall back to their pull path."""


#: ``StreamChannel.take()`` returns this when the producer pushed its
#: final frame — a sentinel, not an exception: takes cross thread/loop
#: boundaries via run_coroutine_threadsafe, where exception identity blurs.
CHANNEL_DONE = object()

_STREAM_WINDOW_DEFAULT = 128


class StreamChannel:
    """Client half of one push stream: a bounded local buffer fed by
    ``_PUSH`` frames from the server, drained by the consumer with zero
    RPCs. Consuming items sends cumulative ``_CREDIT`` frames (one per
    half-window, not per item) — the producer's backpressure signal.

    Buffer bound: the producer stops at ``window`` unacked items, so the
    deque here never holds more than ``window`` + one in-flight batch.
    Thread-safety: ``_items`` is guarded for ``take_available`` callers on
    foreign threads; the awaiting side (``take``) is affine to the owning
    client's event loop.
    """

    def __init__(self, client: "RpcClient", channel_id: str, window: int):
        self._client = client
        self.id = channel_id
        self.window = max(2, window)
        self._lock = threading.Lock()
        self._items: deque = deque()      # rt: guarded-by(_lock)
        self._done = False                # rt: guarded-by(_lock)
        self._broken: Optional[str] = None  # rt: guarded-by(_lock)
        self._event = asyncio.Event()     # loop-affine wakeup
        self._consumed = 0                # items handed to the consumer
        self._credited = 0                # last cumulative credit sent
        self._closed = False
        self._final_credit_sent = False

    # -- fed from the client read loop (client's event loop) --------------
    def _feed(self, items, done: bool) -> None:
        with self._lock:
            self._items.extend(items)
            if done:
                self._done = True
        self._event.set()

    def _fail(self, reason: str) -> None:
        with self._lock:
            if not self._done:
                self._broken = reason
        self._event.set()

    # -- consumer side ----------------------------------------------------
    async def take(self):
        """Next item, ``CHANNEL_DONE`` when the stream completed, or
        raises :class:`ChannelBroken` when the connection died with the
        stream unfinished. Must run on the owning client's loop."""
        while True:
            got = False
            done_now = False
            send_final = False
            item = None
            send_credit = False
            with self._lock:
                if self._items:
                    item = self._items.popleft()
                    got = True
                    self._consumed += 1
                    if (self._consumed - self._credited
                            >= max(1, self.window // 2)):
                        self._credited = self._consumed
                        send_credit = True
                elif self._done:
                    done_now = True
                    send_final = not self._final_credit_sent
                    self._final_credit_sent = True
                elif self._broken is not None:
                    raise ChannelBroken(self._broken)
                else:
                    self._event.clear()
            if done_now:
                # final cumulative credit, closed: tells the producer
                # every item was consumed so it can settle the stream
                # (release the replica's in-flight slot) NOW instead of
                # at consumer GC time
                if send_final:
                    await self._client._send_credit(
                        self.id, self._consumed, closed=True)
                return CHANNEL_DONE
            if send_credit:
                await self._client._send_credit(self.id, self._credited)
            if got:
                return item
            await self._event.wait()

    def is_done(self) -> bool:
        """True once the final frame arrived AND the local buffer is
        fully drained (thread-safe)."""
        with self._lock:
            return self._done and not self._items

    def take_available(self):
        """Drain everything already buffered, without awaiting — the
        proxy's burst coalescing path. Thread-safe; credits are posted to
        the client loop if a half-window was crossed."""
        with self._lock:
            out = list(self._items)
            self._items.clear()
            self._consumed += len(out)
            send = (self._consumed - self._credited
                    >= max(1, self.window // 2))
            if send:
                credited = self._credited = self._consumed
        if send:
            self._client._spawn_on_loop(
                self._client._send_credit(self.id, credited))
        return out

    def close(self) -> None:
        """Consumer abandons the stream: tell the producer to stop
        (closed credit) and deregister. Safe from any thread."""
        if self._closed:
            return
        self._closed = True
        self._client._channels.pop(self.id, None)
        self._client._spawn_on_loop(
            self._client._send_credit(self.id, self._consumed, closed=True))


class ServerConnection:
    """Server half of one accepted connection: the writer a handler can
    push one-way frames down, plus the endpoint registry ``_CREDIT``
    frames dispatch into. Handlers reach their connection through
    :func:`current_server_connection` — the subscribe RPC that opens a
    push stream binds its producer to exactly the connection it arrived
    on, so frames ride the consumer's existing socket."""

    def __init__(self, writer: asyncio.StreamWriter, lock: asyncio.Lock):
        self._writer = writer
        self._lock = lock
        self.alive = True
        # channel_id -> endpoint with on_credit(consumed, closed) /
        # on_disconnect(); mutated only on the server's event loop
        self.endpoints: Dict[str, Any] = {}

    async def push(self, channel_id: str, seq: int, items, done: bool
                   ) -> int:
        """One-way push of a frame batch; returns the wire size in bytes.
        Raises ConnectionLost when the consumer's connection is gone."""
        if not self.alive:
            raise ConnectionLost("push connection closed")
        try:
            async with self._lock:
                n = _write_frame(self._writer,
                                 (_PUSH, seq, channel_id, (items, done)))
                await self._writer.drain()
            return n
        except (ConnectionResetError, BrokenPipeError, RuntimeError) as e:
            # RuntimeError: writer closed under us mid-drain
            self.alive = False
            raise ConnectionLost(f"push failed: {e!r}") from None

    def _on_credit(self, channel_id: str, consumed: int, closed: bool
                   ) -> None:
        ep = self.endpoints.get(channel_id)
        if ep is not None:
            if closed:
                self.endpoints.pop(channel_id, None)
            ep.on_credit(consumed, closed)

    def _on_disconnect(self) -> None:
        self.alive = False
        eps, self.endpoints = list(self.endpoints.values()), {}
        for ep in eps:
            try:
                ep.on_disconnect()
            except Exception:  # noqa: BLE001 — teardown fanout
                pass


_server_conn_var: "contextvars.ContextVar[Optional[ServerConnection]]" = \
    contextvars.ContextVar("rt_server_conn", default=None)


def current_server_connection() -> Optional[ServerConnection]:
    """The connection the currently-executing RPC handler arrived on
    (None outside a handler). Handler tasks inherit it via the context
    snapshot taken when the per-request task is spawned."""
    return _server_conn_var.get()


# Lazily-bound chaos module (util/chaos.py): the rpc layer stays free of
# top-level ray_tpu imports, and the unarmed fast path is one attribute
# check per call.
_chaos_mod = None


def _chaos():
    global _chaos_mod
    if _chaos_mod is None:
        from ray_tpu.util import chaos

        _chaos_mod = chaos
    return _chaos_mod


_reconnect_counter = None


def _observe_reconnect(outcome: str) -> None:
    """``rt_rpc_reconnects_total{outcome=}``: one tick per reconnect dial
    attempt (ok / error), in the reconnecting process's registry. Never
    raises — reconnect telemetry must not break the reconnect."""
    global _reconnect_counter
    try:
        from ray_tpu.util import metrics as M

        if _reconnect_counter is None:
            _reconnect_counter = M.get_or_create(
                M.Counter, "rt_rpc_reconnects_total",
                "RPC client reconnect dial attempts after a dropped "
                "connection, by outcome", tag_keys=("outcome",))
        _reconnect_counter.inc(1.0, {"outcome": outcome})
    except Exception:  # noqa: BLE001
        pass


def bind_host() -> str:
    """The interface servers bind (config ``bind_host``; default loopback).
    Set RT_BIND_HOST=0.0.0.0 on multi-host clusters."""
    from ray_tpu._private.config import get_config

    return get_config().bind_host or "127.0.0.1"


def advertised_host(bind: str) -> str:
    """The address peers should dial for a server bound to ``bind``:
    a wildcard bind advertises this machine's outbound-interface IP
    (UDP connect probe — no packet is sent)."""
    if bind in ("", "127.0.0.1", "localhost"):
        return "127.0.0.1"
    if bind in ("0.0.0.0", "::"):
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        except OSError:
            try:
                return socket.gethostbyname(socket.gethostname())
            except OSError:
                return "127.0.0.1"
        finally:
            s.close()
    return bind


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise RpcError(f"frame too large: {n}")
    body = await reader.readexactly(n)
    return pickle.loads(body)


def _write_frame(writer: asyncio.StreamWriter, msg: Any) -> int:
    body = pickle.dumps(msg, protocol=5)
    writer.write(_LEN.pack(len(body)) + body)
    return len(body)


class RpcServer:
    """Dispatches ``method`` to registered async handlers.

    Handlers are ``async def handler(payload) -> reply``. A handler may take
    arbitrarily long; other requests on the same connection are not blocked.
    """

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 host: Optional[str] = None):
        self._loop = loop
        self._host = host if host is not None else bind_host()
        self._advertise = advertised_host(self._host)
        self._handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_disconnect: Optional[Callable] = None
        self._writers: set = set()
        self.port: int = 0

    def register(self, method: str, handler: Callable) -> None:
        self._handlers[method] = handler

    def register_object(self, obj: Any, prefix: str = "") -> None:
        """Register every ``rpc_*`` coroutine method of ``obj``."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    def set_disconnect_handler(self, fn: Callable) -> None:
        """fn(peer_id) called when a connection identified via 'hello' drops."""
        self._on_disconnect = fn

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle_conn, self._host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    @property
    def address(self) -> str:
        return f"{self._advertise}:{self.port}"

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        peer_id: Optional[str] = None
        write_lock = asyncio.Lock()
        conn = ServerConnection(writer, write_lock)
        # handler tasks spawned below snapshot this context, so any
        # handler can bind a push endpoint to ITS connection
        _server_conn_var.set(conn)
        self._writers.add(writer)
        try:
            while True:
                kind, msg_id, method, payload = await _read_frame(reader)
                if kind == _CREDIT:
                    # one-way consumer credit: no reply slot, no handler
                    conn._on_credit(method, msg_id,
                                    bool(payload and payload.get("closed")))
                    continue
                if method == "hello":
                    peer_id = payload.get("peer_id")
                handler = self._handlers.get(method)
                if handler is None and method == "hello":
                    async def handler(p):  # default hello ack
                        return {"ok": True}
                spawn_task(
                    self._run_handler(handler, method, msg_id, payload,
                                      writer, write_lock))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        finally:
            conn._on_disconnect()
            self._writers.discard(writer)
            if peer_id and self._on_disconnect:
                try:
                    res = self._on_disconnect(peer_id)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    pass
            writer.close()

    async def _run_handler(self, handler, method, msg_id, payload, writer, lock):
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            reply = await handler(payload)
            kind, body = _REP, reply
        except asyncio.CancelledError:
            # server teardown cancelling in-flight handlers: cancellation
            # must stay cancellation — pickling it across the wire as the
            # "reply" would make shutdown look like an application error
            # (and write to a closing transport)
            raise
        except BaseException as e:  # noqa: BLE001 — errors cross the wire
            kind, body = _ERR, e
        try:
            async with lock:
                try:
                    _write_frame(writer, (kind, msg_id, method, body))
                except Exception as pickle_err:
                    # Reply (or raised exception) was unpicklable — the caller
                    # must still get a frame or its future waits forever.
                    _write_frame(writer, (_ERR, msg_id, method,
                                          RpcError(f"unserializable reply for "
                                                   f"{method!r}: {pickle_err!r}")))
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Force-close live connections; 3.12's wait_closed() would block
            # on our long-lived per-connection read loops.
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:
                    pass
            self._writers.clear()


class RpcClient:
    """One multiplexed connection to a server; safe for concurrent calls.

    ``auto_reconnect=True`` makes ``call`` re-dial a dropped connection
    (single-flight) instead of failing forever — the client half of GCS
    head-restart recovery (reference: ``GcsClient`` auto-reconnect,
    ``_raylet.pyx:2346``): long-lived daemons (raylets, pollers) ride out
    a head crash and their next call lands on the resurrected server.
    In-flight calls at drop time still fail with ConnectionLost — only
    NEW calls reconnect; callers with at-most-once concerns keep their
    retry decisions."""

    def __init__(self, address: str, peer_id: str = "",
                 auto_reconnect: bool = False):
        self.address = address
        self._peer_id = peer_id
        self.auto_reconnect = auto_reconnect
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        # push-stream channels multiplexed on this connection; fed by the
        # read loop, failed as a group when the connection drops
        self._channels: Dict[str, StreamChannel] = {}
        self._next_channel = 0
        self._next_id = 0
        self._lock: Optional[asyncio.Lock] = None
        self._closed = False
        self._explicitly_closed = False
        self._reconnect_lock: Optional[asyncio.Lock] = None
        self._reconnect_failed_at = -1e9  # monotonic stamp of last failure

    async def connect(self) -> None:
        host, port = self.address.rsplit(":", 1)
        self._reader, self._writer = await asyncio.open_connection(host, int(port))
        self._lock = asyncio.Lock()
        self._loop = asyncio.get_running_loop()
        self._closed = False
        self._read_task = asyncio.ensure_future(self._read_loop())
        if self._peer_id:
            await self.call("hello", {"peer_id": self._peer_id})

    async def _reconnect(self) -> None:
        if self._reconnect_lock is None:
            self._reconnect_lock = asyncio.Lock()
        async with self._reconnect_lock:
            if not self._closed:
                return  # another caller won the race
            if self._explicitly_closed:
                raise ConnectionLost(
                    f"connection to {self.address} closed")
            # Capped exponential backoff + jitter across several re-dials
            # (reference: gcs_rpc_client retry pacing): a restarted head
            # takes a moment to rebind its port, and an immediate single
            # re-dial both loses that race and — across a fleet of
            # reconnecting raylets — stampedes the resurrected server.
            from ray_tpu._private.config import get_config
            from ray_tpu.core.failure import backoff_with_jitter

            cfg = get_config()
            # Failure memo: callers queued on the lock behind a cycle that
            # just exhausted its attempts fail FAST instead of each
            # re-dialing the full backoff ladder (K serialized callers
            # would otherwise stack K x the cycle time).
            import time as _time

            if (_time.monotonic() - self._reconnect_failed_at
                    < max(2.0, cfg.rpc_reconnect_max_s)):
                raise ConnectionLost(
                    f"reconnect to {self.address} is failing "
                    f"(recent attempt cycle exhausted; retry suppressed)")
            attempts = max(1, cfg.rpc_reconnect_attempts)
            last_err: Optional[BaseException] = None
            for attempt in range(1, attempts + 1):
                await cancel_and_wait(getattr(self, "_read_task", None))
                if self._writer is not None:
                    # release the dead socket before dialing again — daemons
                    # riding out repeated head crashes must not leak one FD
                    # per reconnect cycle
                    self._writer.close()
                    self._writer = None
                try:
                    # each dial is BOUNDED: a blackholed host (SYN dropped,
                    # no RST) or a server that accepts TCP but never
                    # answers hello must burn one attempt, not wedge every
                    # caller serialized on the reconnect lock
                    await asyncio.wait_for(
                        self.connect(), max(2.0, cfg.rpc_reconnect_max_s))
                    _observe_reconnect("ok")
                    self._reconnect_failed_at = -1e9
                    return
                except (OSError, ConnectionLost, asyncio.TimeoutError) as e:
                    # ConnectionLost too: connect() ends with a hello RPC
                    # that dies mid-handshake when the server is still
                    # going down — that's a retryable dial, not a verdict
                    self._closed = True  # a partial dial is cleaned up at
                    last_err = e         # the top of the next iteration
                    _observe_reconnect("error")
                    if attempt < attempts:
                        await asyncio.sleep(backoff_with_jitter(
                            attempt, cfg.rpc_reconnect_base_s,
                            cfg.rpc_reconnect_max_s))
            self._reconnect_failed_at = _time.monotonic()
            raise ConnectionLost(
                f"reconnect to {self.address} failed after {attempts} "
                f"attempt(s): {last_err}") from None

    def open_channel(self, window: int = _STREAM_WINDOW_DEFAULT
                     ) -> StreamChannel:
        """Allocate a push-stream channel on this connection. The caller
        passes the returned ``channel.id`` to the server (via a normal
        RPC) so the producer knows where to push."""
        self._next_channel += 1
        ch = StreamChannel(self, f"ch{id(self):x}-{self._next_channel}",
                           window)
        self._channels[ch.id] = ch
        return ch

    async def _send_credit(self, channel_id: str, consumed: int,
                           closed: bool = False) -> None:
        """One-way cumulative-consumed credit to the producer; never
        raises — a dropped connection already fails the channel via the
        read loop, and credit for a dead producer is moot."""
        try:
            async with self._lock:
                _write_frame(self._writer,
                             (_CREDIT, consumed, channel_id,
                              {"closed": closed} if closed else None))
                await self._writer.drain()
        except Exception:  # noqa: BLE001 — connection gone; channel fails
            pass

    def _spawn_on_loop(self, coro) -> None:
        """Schedule a coroutine on this client's loop from any thread."""
        loop = getattr(self, "_loop", None)
        if loop is None or loop.is_closed():
            coro.close()
            return
        try:
            if asyncio.get_running_loop() is loop:
                spawn_task(coro)
                return
        except RuntimeError:
            pass
        loop.call_soon_threadsafe(spawn_task, coro)

    async def _read_loop(self) -> None:
        try:
            while True:
                kind, msg_id, method, body = await _read_frame(self._reader)
                if kind == _PUSH:
                    ch = self._channels.get(method)
                    if ch is not None:
                        items, done = body
                        ch._feed(items, done)
                        if done:
                            self._channels.pop(method, None)
                    else:
                        # consumer already closed the channel: tell the
                        # producer to stop pushing into the void
                        spawn_task(self._send_credit(method, msg_id,
                                                     closed=True))
                    continue
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == _ERR:
                    fut.set_exception(body if isinstance(body, BaseException)
                                      else RpcError(str(body)))
                else:
                    fut.set_result(body)
        # rt: lint-allow(except-discipline) cancel == connection teardown
        # here; the loop exits via finally, failing every pending future
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError,
                asyncio.CancelledError):
            pass
        finally:
            self._closed = True
            # push channels die with the connection: wake every consumer
            # with ChannelBroken so it can fall back to its pull path
            chans, self._channels = list(self._channels.values()), {}
            for ch in chans:
                ch._fail(f"connection to {self.address} lost")
            for fut in self._pending.values():
                try:
                    if not fut.done():
                        fut.set_exception(
                            ConnectionLost(f"connection to {self.address} lost"))
                        # Mark the exception retrieved: callers abandoned at
                        # teardown (e.g. a timed-out wait_for) never await this
                        # future, and asyncio would spam "Future exception was
                        # never retrieved" at GC. A live awaiter still sees the
                        # ConnectionLost raised from `await fut`.
                        fut.exception()
                except RuntimeError:
                    pass  # loop already closed during interpreter teardown
            self._pending.clear()

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        c = _chaos()
        # chaos rpc partition sites — a few methods are BELOW the
        # injection plane: 'hello' (dropping the handshake would leave a
        # connected-but-anonymous client whose server-side disconnect
        # tracking never engages, outlasting the partition) and the chaos
        # control loop itself ('heartbeat' carries the plan revision,
        # 'chaos_status' carries the plan, 'chaos_arm' is the worker
        # forward) — an armed plan must never block its own rollout,
        # update, or disarm; heartbeat partitions have their dedicated
        # raylet.heartbeat_drop site
        if c._STATE is not None and method not in (
                "hello", "heartbeat", "chaos_status", "chaos_arm"):
            f = c.maybe_fire("rpc.delay", target=method)
            if f is not None:
                await asyncio.sleep(float(f.get("delay_s", 0.05)))
            f = c.maybe_fire("rpc.drop", target=method)
            if f is not None:
                raise ConnectionLost(
                    f"chaos: dropped rpc {method!r} to {self.address}")
        if self._closed:
            if not self.auto_reconnect or self._explicitly_closed:
                raise ConnectionLost(f"connection to {self.address} closed")
            await self._reconnect()
        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            msg_id = self._next_id
            self._next_id += 1
            self._pending[msg_id] = fut
            _write_frame(self._writer, (_REQ, msg_id, method, payload))
            await self._writer.drain()
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def close(self) -> None:
        self._closed = True
        self._explicitly_closed = True
        if self._writer is not None:
            self._writer.close()
        await cancel_and_wait(getattr(self, "_read_task", None))


class EventLoopThread:
    """A dedicated background asyncio loop — the process's io_service
    (reference: ``instrumented_io_context``)."""

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True, name=name)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the loop from a sync thread; block for result.

        Fails FAST once the loop is stopped: run_coroutine_threadsafe on a
        no-longer-spinning loop returns a future that never resolves, and a
        caller blocked on it forever while holding a lock is a process-wide
        deadlock (stale serve pollers held _controller_lock this way)."""
        if self._stopped or self.loop.is_closed():
            coro.close()
            raise RuntimeError("event loop thread is stopped")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro) -> None:
        if self._stopped or self.loop.is_closed():
            coro.close()
            return
        # NOT run_coroutine_threadsafe: its task<->concurrent-future pair is
        # an unreferenced cycle once the caller drops the return value, and
        # the GC can then collect the task mid-flight (see spawn_task).
        self.loop.call_soon_threadsafe(spawn_task, coro)

    def stop(self) -> None:
        self._stopped = True  # run()/spawn() fail fast from here on

        # Cancel and drain outstanding tasks first so the loop doesn't warn
        # "Task was destroyed but it is pending!" at GC time.
        async def _drain():
            tasks = [t for t in asyncio.all_tasks()
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(_drain(), self.loop).result(2)
        except Exception:
            pass
        # Tasks that survived the bounded drain would pin _BG_TASKS forever
        # (their done-callback never fires once the loop stops).
        for t in [t for t in _BG_TASKS if t.get_loop() is self.loop]:
            _BG_TASKS.discard(t)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=2)
        if not self._thread.is_alive():
            try:
                self.loop.close()
            except Exception:
                pass


class ConnectionPool:
    """Address -> RpcClient cache (reference: core_worker_client pool)."""

    def __init__(self, peer_id: str = ""):
        self._peer_id = peer_id
        self._clients: Dict[str, RpcClient] = {}
        self._locks: Dict[str, asyncio.Lock] = {}

    async def get(self, address: str) -> RpcClient:
        client = self._clients.get(address)
        if client is not None and not client._closed:
            return client
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            client = self._clients.get(address)
            if client is not None and not client._closed:
                return client
            client = RpcClient(address, self._peer_id)
            await client.connect()
            self._clients[address] = client
            return client

    def invalidate(self, address: str) -> None:
        self._clients.pop(address, None)

    async def close_all(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
