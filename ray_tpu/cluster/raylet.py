"""Raylet: the per-node control plane (worker pool + local scheduler + object
plane endpoints).

Reference analog: ``src/ray/raylet/`` — ``NodeManager`` (lease/dispatch RPCs),
``WorkerPool`` (process spawning + idle reuse keyed by environment),
``LocalTaskManager`` (resource-gated FIFO dispatch), ``ObjectManager``
(node-to-node transfer by directory lookup). Redesigns:
  - Tasks are pushed raylet→worker and the submitter's RPC is held open until
    completion, so small results ride the reply chain back to the OWNER's
    memory store (the reference gets the same effect with worker→worker
    ``PushNormalTask`` after a lease; fewer moving parts here, same ownership
    semantics).
  - TPU chips are per-instance resources: a task/actor holding chips gets a
    dedicated worker process pinned via TPU_VISIBLE_CHIPS at spawn, cached
    keyed by its chip set (reference: worker cache keyed by runtime-env hash).
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu import _native
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ObjectID
from ray_tpu.core import failure as F
from ray_tpu.core.resources import CPU, NodeResources, ResourceSet, TPU
from ray_tpu.cluster.object_store import PlasmaStore
from ray_tpu.cluster.rpc import (
    ConnectionLost,
    ConnectionPool,
    RpcClient,
    RpcServer,
    cancel_and_wait,
    spawn_task,
)
from ray_tpu.exceptions import WorkerCrashedError
from ray_tpu.scheduler.policy import strategy_allows_local
from ray_tpu.util import chaos as C
from ray_tpu.util import metrics as M
from ray_tpu.util.profiling import format_current_stacks


class _WorkerEntry:
    def __init__(self, worker_id: str, proc: subprocess.Popen, key: Tuple,
                 loop: asyncio.AbstractEventLoop):
        self.worker_id = worker_id
        self.proc = proc
        self.key = key                      # (chip_tuple, runtime_env_hash)
        self.address: Optional[str] = None
        self.client: Optional[RpcClient] = None
        self.ready = loop.create_future()
        self.busy = False
        self.is_actor_worker = False
        self.actor_id: Optional[str] = None
        self.assignment: Dict[str, List[int]] = {}
        self.oom_killed = False
        self.job_id: Optional[str] = None  # current job, for log routing
        self.idle_since: Optional[float] = None  # monotonic; None = busy
        self.current_task: Optional[str] = None  # fn_name while executing


class _BundleState:
    """A committed PG bundle: a carved-out resource pool on this node.

    The bundle holds ``req`` (+ specific chips) against the node; tasks and
    actors placed into the bundle allocate from this pool, not the node's.
    """

    def __init__(self, req: ResourceSet, node_assignment: Dict[str, List[int]]):
        self.node_req = req
        self.node_assignment = node_assignment
        self.pool = NodeResources(req.to_dict())
        if TPU in node_assignment:
            self.pool._free_tpu_chips = list(node_assignment[TPU])
        self.committed = False


# the worker-pool key of a plain worker: no pinned chips, no runtime env —
# the only kind the prestart floor maintains and actor creation may adopt
_WARM_KEY: Tuple = ((), None)


class _SchedQueues:
    """Per-scheduling-class FIFO queues dispatched round-robin (reference:
    the LocalTaskManager's per-``SchedulingClass`` queues,
    ``local_task_manager.h`` — the structure that keeps a 1-task probe from
    waiting out a 5k-deep bulk flood).

    A scheduling class is ``(owner, fn_name, resource shape)`` — the
    granularity at which the reference keys dispatch. FIFO order is
    preserved WITHIN a class; classes take turns claiming resources, and a
    class that just dispatched rotates to the back of the order.
    """

    def __init__(self):
        self._classes = collections.OrderedDict()  # key -> deque of items
        self._deque = collections.deque
        self._len = 0
        self._expiring = 0  # queued items carrying a deadline stamp

    @staticmethod
    def _strategy_token(strategy) -> Tuple:
        # Canonical hashable form of a SchedulingStrategy. The strategy is
        # part of the class (reference: SchedulingClassDescriptor): a head
        # that MUST route elsewhere (hard NODE_AFFINITY/NODE_LABEL) and
        # can't — peers full — would otherwise head-of-line-block locally
        # runnable tasks of the same shape forever.
        kind = getattr(strategy, "kind", "DEFAULT")
        if kind == "NODE_AFFINITY":
            return (kind, strategy.node_id_hex, bool(strategy.soft))
        if kind == "NODE_LABEL":
            def canon(d):
                return tuple(sorted(
                    (k, tuple(v) if isinstance(v, list) else v)
                    for k, v in (d or {}).items()))
            return (kind, canon(strategy.hard), canon(strategy.soft))
        return (kind,)

    @staticmethod
    def class_key(payload: Dict) -> Tuple:
        # PG identity is part of the class: bundles are independent pools,
        # so a head blocked on a saturated bundle must not queue-block
        # same-shaped tasks bound for an idle bundle (PG tasks never
        # spill — without this split they could starve behind it forever)
        pg = payload.get("pg") or None
        return (payload.get("owner") or "",
                payload.get("fn_name") or "",
                tuple(sorted((payload.get("resources") or {}).items())),
                (pg["pg_id"], pg.get("bundle_index")) if pg else None,
                _SchedQueues._strategy_token(payload.get("strategy")))

    @staticmethod
    def class_label(key: Tuple) -> str:
        return key[1] or "anonymous"

    def push(self, item: Dict) -> None:
        q = self._classes.get(item["skey"])
        if q is None:
            q = self._classes[item["skey"]] = self._deque()
        q.append(item)
        self._len += 1
        if item.get("expires") is not None:
            self._expiring += 1

    @property
    def expiring(self) -> int:
        """Queued items with a deadline — lets the heartbeat sweep skip
        its O(total queued) scan when nothing can expire."""
        return self._expiring

    def __len__(self) -> int:
        return self._len

    def depth(self, key: Tuple) -> int:
        q = self._classes.get(key)
        return len(q) if q else 0

    def head(self, key: Tuple) -> Optional[Dict]:
        q = self._classes.get(key)
        return q[0] if q else None

    def pop_head(self, key: Tuple) -> Optional[Dict]:
        q = self._classes.get(key)
        if not q:
            return None
        item = q.popleft()
        self._len -= 1
        if item.get("expires") is not None:
            self._expiring -= 1
        if not q:
            self._classes.pop(key, None)
        return item

    def remove(self, item: Dict) -> bool:
        """O(class depth) removal — only the spillback path (rare) and the
        deadline sweep use it."""
        q = self._classes.get(item["skey"])
        if not q:
            return False
        try:
            q.remove(item)
        except ValueError:
            return False
        self._len -= 1
        if item.get("expires") is not None:
            self._expiring -= 1
        if not q:
            self._classes.pop(item["skey"], None)
        return True

    def rotate(self, key: Tuple) -> None:
        if key in self._classes:
            self._classes.move_to_end(key)

    def window(self, key: Tuple, n: int) -> List[Dict]:
        """The first ``n`` items of a class (the spillback scan window)."""
        q = self._classes.get(key)
        return list(itertools.islice(q, n)) if q else []

    def keys(self) -> List[Tuple]:
        return list(self._classes)

    def items(self):
        """Every queued item, class by class (deadline sweep)."""
        for q in list(self._classes.values()):
            yield from list(q)

    def first_n(self, n: int):
        """Up to ``n`` queued items WITHOUT copying class deques — the
        heartbeat demand scan must stay O(n), not O(total queued)."""
        for q in list(self._classes.values()):
            if n <= 0:
                return
            for item in itertools.islice(q, n):
                n -= 1
                yield item

    def by_class(self) -> List[Tuple[str, int, float]]:
        """(label, depth, oldest enqueue monotonic) per class, deepest
        first. Labels collide across owners on purpose — telemetry
        cardinality stays bounded by distinct function names."""
        agg: Dict[str, Tuple[int, float]] = {}
        for key, q in list(self._classes.items()):
            if not q:
                continue
            label = self.class_label(key)
            depth, oldest = agg.get(label, (0, float("inf")))
            agg[label] = (depth + len(q),
                          min(oldest, q[0].get("t_enq", q[0]["t"])))
        return sorted(((lb, d, t) for lb, (d, t) in agg.items()),
                      key=lambda r: -r[1])


class Raylet:
    def __init__(self, node_id: str, session_name: str, gcs_address: str,
                 resources: Dict[str, float], labels: Dict[str, str],
                 loop: asyncio.AbstractEventLoop):
        self.node_id = node_id
        self.session_name = session_name
        self.gcs_address = gcs_address
        self.node = NodeResources(resources, labels)
        self.loop = loop
        self.store = PlasmaStore(session_name)
        self.server = RpcServer(loop)
        self.server.register_object(self)
        self.server.set_disconnect_handler(self._on_peer_disconnect)
        self._gcs: Optional[RpcClient] = None
        self._pool = ConnectionPool(peer_id=f"raylet:{node_id}")
        self._workers: Dict[str, _WorkerEntry] = {}
        self._idle: Dict[Tuple, List[_WorkerEntry]] = {}
        # concurrent worker-process boots allowed (see _get_worker):
        # enough to hide boot latency, few enough that a task burst can't
        # fork-bomb a small host
        self._spawn_slots = max(4, 2 * (os.cpu_count() or 1))
        # Pending task payloads + futures, organized per scheduling class
        # and dispatched round-robin (the overload-robust replacement for
        # the old FIFO list — see _SchedQueues).
        self._squeue = _SchedQueues()
        self._inflight: Dict[str, Dict] = {}  # task_id -> resource state
        self._task_futures: Dict[str, "asyncio.Future"] = {}  # dedup joins
        self._replies: Dict[str, Dict] = {}  # task_id -> successful reply
        self._bundles: Dict[Tuple[str, int], _BundleState] = {}
        self._dispatch_event = asyncio.Event()
        # worker-log ring (filled by _log_pump_loop, drained by poll_logs)
        self._log_buf: "collections.deque" = collections.deque(maxlen=10000)
        self._log_seq = 0
        self._log_event = asyncio.Event()
        self._local_objects: set = set()
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        # --- object durability (reference: LocalObjectManager spilling,
        # plasma EvictionPolicy) ---
        cfg = get_config()
        self._store_capacity = (cfg.object_store_memory_bytes
                                or cfg.object_store_default_cap_bytes)
        self._spill_dir = (cfg.object_spilling_dir
                           or os.path.join(cfg.session_dir_root, session_name,
                                           "spill", node_id))
        # oid_hex -> {"size": int, "t": last-access, "spilled": bool}
        self._object_meta: Dict[str, Dict[str, Any]] = {}
        # Get-time pins (reference: ``PinObjectIDs``,
        # ``raylet/node_manager.h:515-555``): a getter pins its whole ref set
        # before resolution so concurrent restores can't mutually re-evict
        # each other's objects between fetch-ok and the shm read. Refcounted;
        # a stale pin (crashed getter) expires after _PIN_TTL_S.
        # oid_hex -> {"count": int, "t": monotonic-of-last-pin}
        self._pinned: Dict[str, Dict[str, float]] = {}
        # In-flight remote pulls: chunked transfer holds the .building file
        # across awaits, so concurrent fetches of one object must join the
        # first pull, not race its O_EXCL create (reference: PullManager
        # dedups by object id).
        self._pulls: Dict[str, asyncio.Future] = {}
        # in-flight client-mode uploads: oid -> (buffer, started_at);
        # stale entries (client died mid-upload) purged by the reap loop
        self._client_uploads: Dict[str, Tuple[Any, float]] = {}
        # Running sum of in-memory (non-spilled) object bytes, so the
        # per-unpin spill precheck is O(1) not O(#objects). Maintained by
        # _touch / _spill_blocking / rpc_free_objects; the spill thread
        # recomputes exactly under its lock before acting.
        self._in_mem_bytes = 0
        # spill/restore file IO runs here, never on the event loop — the
        # raylet must keep dispatching while bytes hit the disk (reference:
        # dedicated Python IO workers in LocalObjectManager)
        self._spill_exec = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rt-spill")
        self._spill_lock = threading.Lock()
        # Scheduler queue telemetry (reference: the raylet's
        # scheduler_stats in GcsNodeManager reports): queue depth rides
        # every heartbeat; per-dispatch queue wait feeds a histogram. Both
        # series land on the Prometheus push — from THIS process's registry
        # when no driver shares it (standalone node daemon), or via the
        # driver's pusher in an in-process cluster. RT_QUEUE_TELEMETRY=0
        # reduces the dispatch path to one predicate check.
        self._telemetry = os.environ.get(
            "RT_QUEUE_TELEMETRY", "1") not in ("", "0", "false")
        self._tele_metrics: Optional[Dict[str, Any]] = None
        self._tele_pushed = 0.0
        # Memory-plane counters (cumulative; surfaced by rpc_memory_report
        # and `rt memory`, twinned as rt_object_* / rt_oom_kills_total on
        # the Prometheus push). Mutated from the loop AND the spill
        # executor thread — single increments only, drift-free enough for
        # telemetry.
        self._mem_stats: Dict[str, float] = {
            "spills": 0, "spill_bytes": 0, "spill_seconds": 0.0,
            "restores": 0, "restore_bytes": 0, "restore_seconds": 0.0,
            "pin_purges": 0, "oom_kills": 0}
        self._rss_reported: set = set()  # worker_ids with a live RSS gauge
        # client-side failure-emission rate limit (see _failure_event)
        self._failure_limiter = F.EmitLimiter()
        # --- GCS-outage degraded mode (reference: the raylet surviving a
        # GCS failover, gcs_client reconnection) --- while the GCS is
        # unreachable this raylet KEEPS executing local work; bookkeeping
        # updates (object locations, death reports) defer here and replay
        # in order on resync. Entered by the heartbeat loop or the first
        # failed publish; exited by the first successful heartbeat.
        self._degraded_since: Optional[float] = None
        self._deferred_gcs: "collections.deque" = collections.deque(
            maxlen=10000)
        self._deferred_dropped = 0  # overflow evictions during an outage
        self._flushing = False      # single-flight deferred-replay guard
        # last chaos-plan revision this raylet synced from the GCS
        self._chaos_seen_rev = 0
        self._hb_drops = 0  # consecutive chaos-dropped heartbeats
        # --- overload-robust control plane (fair dispatch / warm pool /
        # admission / deadlines) --- cumulative accounting surfaced by
        # node_stats, the heartbeat's sched summary, `rt status` and the
        # rt_sched_* / rt_worker_pool_* Prometheus series.
        self._sched_stats: Dict[str, int] = {
            "warm_hits": 0, "cold_spawns": 0, "actor_adoptions": 0,
            "prestarted": 0, "backpressure": 0, "deadline_evictions": 0}
        # per-class recent queue waits: label -> deque[(t_mono, wait_s)];
        # feeds the heartbeat's wait_p99_s (the `rt doctor` starvation
        # finding) without keeping one histogram per class
        self._class_waits: Dict[str, Any] = {}
        self._class_gauge_labels: set = set()  # live rt_sched_class gauges
        self._prestarting = 0  # warm-pool spawns currently booting
        # raylet->GCS task-event chatter batches here and ships as ONE
        # coalesced task_events RPC per flush window (the submit hot path
        # used to pay 3 GCS round-trips per task)
        self._task_event_buf: "collections.deque" = collections.deque(
            maxlen=10000)
        self._task_event_flushing = False
        self._task_event_kick = asyncio.Event()  # terminal-state fast path

    _QUEUE_WAIT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 1.0, 5.0, 15.0,
                           60.0, 300.0, 900.0)
    _SPILL_BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                      5.0, 15.0, 60.0)

    def _telemetry_metrics(self) -> Dict[str, Any]:
        if self._tele_metrics is None:
            self._tele_metrics = {
                "queue_depth": M.get_or_create(
                    M.Gauge, "rt_raylet_queue_depth",
                    "Pending tasks in the raylet dispatch queue",
                    tag_keys=("node_id",)),
                "queue_wait": M.get_or_create(
                    M.Histogram, "rt_task_queue_wait_seconds",
                    "Raylet queue wait per dispatched task "
                    "(enqueue to dispatch claim)",
                    boundaries=self._QUEUE_WAIT_BUCKETS,
                    tag_keys=("node_id",)),
                "store_bytes": M.get_or_create(
                    M.Gauge, "rt_object_store_bytes",
                    "Per-node object store bytes by state "
                    "(in_memory / spilled / pinned)",
                    tag_keys=("node_id", "state")),
                "spill_hist": M.get_or_create(
                    M.Histogram, "rt_object_spill_seconds",
                    "Disk-spill IO time per spilled object",
                    boundaries=self._SPILL_BUCKETS, tag_keys=("node_id",)),
                "restore_hist": M.get_or_create(
                    M.Histogram, "rt_object_restore_seconds",
                    "Spill-restore IO time per restored object",
                    boundaries=self._SPILL_BUCKETS, tag_keys=("node_id",)),
                "worker_rss": M.get_or_create(
                    M.Gauge, "rt_worker_rss_bytes",
                    "Resident set size of each live worker process",
                    tag_keys=("node_id", "worker_id")),
                "oom_kills": M.get_or_create(
                    M.Counter, "rt_oom_kills_total",
                    "Workers killed by the raylet memory monitor",
                    tag_keys=("node_id",)),
                "pin_purges": M.get_or_create(
                    M.Counter, "rt_object_pin_purges_total",
                    "Leaked get-pins purged by the TTL timer "
                    "(crashed getters)",
                    tag_keys=("node_id",)),
                "class_depth": M.get_or_create(
                    M.Gauge, "rt_sched_class_queue_depth",
                    "Pending tasks per scheduling class in the raylet's "
                    "round-robin dispatch queues",
                    tag_keys=("node_id", "sched_class")),
                "warm_hits": M.get_or_create(
                    M.Counter, "rt_worker_pool_warm_hits_total",
                    "Dispatches served by a warm pooled worker instead "
                    "of a fresh process spawn",
                    tag_keys=("node_id", "kind")),
                "backpressure": M.get_or_create(
                    M.Counter, "rt_sched_backpressure_total",
                    "Task submissions bounced with a backpressure reply "
                    "(per-class admission bound)",
                    tag_keys=("node_id",)),
                "deadline_evictions": M.get_or_create(
                    M.Counter, "rt_sched_deadline_evictions_total",
                    "Queued tasks shed because their deadline_s budget "
                    "expired before dispatch",
                    tag_keys=("node_id",)),
            }
        return self._tele_metrics

    # ---- lifecycle ----------------------------------------------------------
    async def start(self, port: int = 0) -> str:
        await self.server.start(port)
        self._gcs = RpcClient(self.gcs_address,
                              peer_id=f"raylet:{self.node_id}",
                              auto_reconnect=True)
        await self._gcs.connect()
        await self._gcs.call("register_node", {
            "node_id": self.node_id, "address": self.server.address,
            "resources": self.node.total.to_dict(),
            "labels": dict(self.node.labels)})
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._dispatch_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))
        self._tasks.append(asyncio.ensure_future(self._log_pump_loop()))
        if get_config().memory_usage_threshold < 1.0:
            self._tasks.append(
                asyncio.ensure_future(self._memory_monitor_loop()))
        return self.server.address

    async def stop(self, destroy_store: bool = False) -> None:
        self._stopped = True
        await cancel_and_wait(*self._tasks)
        self._tasks.clear()
        for w in list(self._workers.values()):
            try:
                w.proc.terminate()
            except ProcessLookupError:
                pass
        if self._gcs is not None:
            await self._gcs.close()
        await self._pool.close_all()
        await self.server.stop()
        # The shm session dir is SHARED by all nodes of the session (same
        # host); only the session owner destroys it (ClusterHandle.shutdown).
        if destroy_store:
            self.store.destroy()

    async def _heartbeat_loop(self) -> None:
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            f = C.maybe_fire("raylet.heartbeat_drop")
            if f is not None:
                # simulated raylet<->GCS partition: ONLY the beat is not
                # sent (telemetry push + dispatch wake below still run —
                # local work must not stall); enough consecutive drops
                # cross node_death_timeout_s and the GCS declares this
                # node dead (then resurrects it when the beats resume)
                self._chaos_stamp("raylet.heartbeat_drop", f)
                self._hb_drops += 1
                if self._hb_drops % 5 == 0:
                    # an UNBOUNDED drop plan must still honor `rt chaos
                    # disarm`: probe the plan revision out-of-band every
                    # few drops (the heartbeat itself stays dropped, so
                    # the node-death semantics are untouched)
                    spawn_task(self._probe_chaos_rev())
            else:
                self._hb_drops = 0
                await self._heartbeat_once()
            if self._telemetry:
                await self._push_telemetry()
            if len(self._squeue):
                # deadline budgets are enforced on a sweep too, not just at
                # the dispatch head: stale work deep in a blocked class is
                # shed while it is still cheap to shed
                self._evict_expired()
                # periodic wake so waiting tasks re-evaluate spillback even
                # when no local resource event fires
                self._dispatch_event.set()

    async def _heartbeat_once(self) -> None:
        try:
            # queued-but-unplaced demand rides the heartbeat so the
            # autoscaler can bin-pack it onto prospective node types
            # (reference: resource_demand_scheduler's load report)
            demands: Dict[Tuple, int] = {}
            for item in self._squeue.first_n(100):
                key = tuple(sorted(
                    item["payload"].get("resources", {}).items()))
                demands[key] = demands.get(key, 0) + 1
            # bounded: a hung-but-connected GCS must trip the transient
            # path into degraded mode, not wedge the maintenance loop
            reply = await self._gcs.call("heartbeat", {
                "node_id": self.node_id,
                "available": self.node.available.to_dict(),
                "queue_depth": len(self._squeue),
                "sched": self._sched_summary(),
                "queued_demands": [
                    {"resources": dict(k), "count": c}
                    for k, c in list(demands.items())[:20]]},
                timeout=10.0)
            if reply.get("unknown"):
                # The GCS restarted and lost the node table (nodes are
                # deliberately not snapshotted): re-register under the
                # SAME node id, then re-publish actors + locations OFF
                # this loop (stalling heartbeats past the death timeout
                # would get the fresh registration killed again).
                await self._gcs.call("register_node", {
                    "node_id": self.node_id,
                    "address": self.server.address,
                    "resources": self.node.total.to_dict(),
                    "labels": dict(self.node.labels)}, timeout=10.0)
                spawn_task(self._reattach_after_gcs_restart())
            if reply.get("resurrected"):
                # off the heartbeat loop: a long republish here would
                # stall heartbeats past node_death_timeout_s and
                # re-enter the death/resurrect cycle
                spawn_task(self._reconcile_after_resurrection())
            rev = reply.get("chaos_rev")
            if rev is not None and rev != self._chaos_seen_rev:
                spawn_task(self._sync_chaos(
                    rev, reply.get("chaos_armed", True)))
            if self._degraded_since is not None and not self._flushing:
                # the GCS is reachable again: replay deferred updates and
                # leave degraded mode — OFF this loop (a 10k-entry replay
                # awaited here would stall beats past node_death_timeout_s
                # and re-enter the death/resurrect cycle); single-flight
                self._flushing = True
                spawn_task(self._flush_deferred_guarded())
            for ev in C.drain_events():
                # rpc.* chaos fires buffered in-process (the rpc layer
                # has no GCS handle) ship from here
                F.emit_raw(spawn_task, self._gcs, ev)
        except Exception as e:  # noqa: BLE001
            # GCS unreachable: enter degraded mode — local dispatch
            # keeps running, bookkeeping defers until resync. Only
            # TRANSPORT failures count (same discipline as _gcs_publish);
            # an application error from a healthy GCS is swallowed like
            # the pre-degraded-mode loop did.
            if self._is_transient(e) and self._degraded_since is None:
                self._degraded_since = time.monotonic()

    async def _push_telemetry(self) -> None:
        """Queue-depth gauge + registry push. A standalone node daemon has
        no driver metrics pusher, so the raylet ships its own registry
        snapshot to the @metrics/ KV; when a driver shares this process
        (in-process test cluster) its pusher covers the shared registry and
        this path skips the write (double-pushed histograms would double
        their counts in the merged Prometheus page)."""
        try:
            m = self._telemetry_metrics()
            m["queue_depth"].set(len(self._squeue),
                                 {"node_id": self.node_id})
            now = time.monotonic()
            if now - self._tele_pushed < 5.0:
                return
            # O(#objects) scan and /proc reads at the push cadence only —
            # samples set more often than they are shipped are wasted work
            self._set_store_gauges(m)
            self._set_class_gauges(m)
            self._update_worker_rss(m)
            if ray_tpu.is_initialized():
                self._tele_pushed = now
                return  # the driver's pusher owns this registry
            await self._gcs.call("kv_put", {
                "key": f"{M._KV_PREFIX}raylet:{self.node_id}",
                "value": json.dumps({
                    "t": time.time(),
                    "metrics": M._registry.snapshot()}).encode()})
            self._tele_pushed = now
        except Exception:  # noqa: BLE001 — telemetry must never kill
            pass  # the heartbeat loop

    def _store_state_bytes(self) -> Dict[str, int]:
        """One pass over the object meta: bytes by state. ``pinned`` counts
        live-pinned in-memory bytes (a subset of in_memory, like the
        reference's pinned accounting)."""
        now = time.monotonic()
        in_mem = spilled = pinned = 0
        for oid_hex, meta in list(self._object_meta.items()):
            if meta.get("spilled"):
                spilled += meta["size"]
            else:
                in_mem += meta["size"]
                if self._is_pinned(oid_hex, now):
                    pinned += meta["size"]
        return {"in_memory": in_mem, "spilled": spilled, "pinned": pinned}

    def _set_store_gauges(self, m: Dict[str, Any]) -> None:
        for state, v in self._store_state_bytes().items():
            m["store_bytes"].set(v, {"node_id": self.node_id,
                                     "state": state})

    def _set_class_gauges(self, m: Dict[str, Any]) -> None:
        """rt_sched_class_queue_depth per live scheduling class; classes
        that drained remove their samples so the page doesn't accumulate
        one stale series per function name ever submitted."""
        live: set = set()
        for label, depth, _oldest in self._squeue.by_class():
            live.add(label)
            m["class_depth"].set(depth, {"node_id": self.node_id,
                                         "sched_class": label})
        for label in self._class_gauge_labels - live:
            m["class_depth"].remove({"node_id": self.node_id,
                                     "sched_class": label})
        self._class_gauge_labels = live

    def _class_wait_p99(self, label: str,
                        now: float, window_s: float = 60.0
                        ) -> Optional[float]:
        dq = self._class_waits.get(label)
        if not dq:
            return None
        waits = sorted(w for t, w in dq if now - t <= window_s)
        if not waits:
            self._class_waits.pop(label, None)  # stale class: stop reporting
            return None
        return waits[min(len(waits) - 1, int(0.99 * len(waits)))]

    def _sched_summary(self) -> Dict[str, Any]:
        """The scheduling plane's health snapshot: per-class depth +
        queue-wait p99 + oldest-waiter age (what `rt doctor` grades for
        starvation), and warm-pool occupancy / hit accounting. Rides every
        heartbeat into the GCS node table -> `rt status`, the dashboard
        Nodes tab and doctor findings."""
        now = time.monotonic()
        if len(self._class_waits) > 256:
            # bound the per-class wait rings: a job churning through many
            # distinct fn names must not grow this forever — drop labels
            # whose newest sample went stale
            for label, dq in list(self._class_waits.items()):
                if not dq or now - dq[-1][0] > 600.0:
                    self._class_waits.pop(label, None)
        classes = []
        rows = self._squeue.by_class()
        pick = rows[:10]
        if len(rows) > 10:
            # depth alone must not truncate away a starving shallow class
            # (the exact case doctor's per-class finding exists for) —
            # union in the oldest waiters
            seen = {r[0] for r in pick}
            pick += [r for r in sorted(rows, key=lambda r: r[2])
                     if r[0] not in seen][:5]
        for label, depth, oldest_t in pick:
            entry: Dict[str, Any] = {
                "class": label, "depth": depth,
                "oldest_wait_s": round(max(0.0, now - oldest_t), 3)}
            p99 = self._class_wait_p99(label, now)
            if p99 is not None:
                entry["wait_p99_s"] = round(p99, 3)
            classes.append(entry)
        s = self._sched_stats
        served = s["warm_hits"] + s["cold_spawns"]
        return {
            "classes": classes,
            "warm": {
                # warm-pool occupancy = adoptable/prestartable workers
                # ONLY (the _WARM_KEY list); env- or chip-keyed idle
                # workers can't serve a cold plain dispatch — counting
                # them would claim a full pool while every hit misses
                "idle": len(self._idle.get(_WARM_KEY, ())),
                "idle_total": sum(len(v) for v in self._idle.values()),
                "floor": get_config().worker_prestart_floor,
                "warm_hits": s["warm_hits"],
                "cold_spawns": s["cold_spawns"],
                "actor_adoptions": s["actor_adoptions"],
                "prestarted": s["prestarted"],
                "hit_rate": round(s["warm_hits"] / served, 3) if served
                else None},
            "backpressure_total": s["backpressure"],
            "deadline_evictions_total": s["deadline_evictions"],
            # queued+running is the load number the GCS's cross-node
            # imbalance CoV (rt_sched_node_imbalance) is computed over
            "running": len(self._inflight),
        }

    def _update_worker_rss(self, m: Dict[str, Any]) -> None:
        """rt_worker_rss_bytes per live worker; dead workers' samples are
        removed so the page doesn't accumulate stale series."""
        by_pid = {e.proc.pid: e.worker_id
                  for e in self._workers.values() if e.proc.poll() is None}
        live: set = set()
        for pid, rss in _native.process_memory(list(by_pid)):
            wid = by_pid.get(pid)
            if wid is None:
                continue
            live.add(wid)
            m["worker_rss"].set(rss, {"node_id": self.node_id,
                                      "worker_id": wid})
        for wid in self._rss_reported - live:
            m["worker_rss"].remove({"node_id": self.node_id,
                                    "worker_id": wid})
        self._rss_reported = live

    def _mem_event(self, kind: str, **fields) -> None:
        """Fire-and-forget memory instant event to the GCS mem-event store
        (spill / restore / oom_kill): feeds ``ray_tpu.timeline()`` instant
        markers and the `rt memory --oom` post-mortem replay."""
        async def _send():
            try:
                msg = {"kind": kind, "node_id": self.node_id,
                       "t": time.time()}
                msg.update(fields)
                await self._gcs.call("mem_event", msg)
            except Exception:  # noqa: BLE001 — observability only
                pass

        spawn_task(_send())

    def _failure_event(self, category: str, message: str, **fields) -> None:
        """Categorized FailureEvent to the GCS failure store
        (core/failure.py taxonomy): feeds `rt errors`, `/api/errors`, the
        timeline's errors lane and ``rt_failures_total{category=}``
        (counted GCS-side — emitters never double-count). Rate-limited
        per (category, subject-kind): a burst of 5000 tasks failing the
        same way (bundle gone, infeasible) must not stream one RPC per
        task or evict the feed with unique-task rows."""
        key = (category, fields.get("name") or fields.get("actor_id")
               or fields.get("worker_id") or message)
        if not self._failure_limiter.allow(key):
            return
        F.emit(spawn_task, self._gcs, category, message,
               node_id=self.node_id, **fields)

    # ---- chaos plane (util/chaos.py) ---------------------------------------
    def _chaos_stamp(self, site: str, fault: Dict, **fields) -> None:
        """Stamp one chaos-origin FailureEvent for a fault fired in this
        raylet. Thread-safe: callable from the spill executor as well as
        the event loop (the send is scheduled onto the loop)."""
        payload = C.event_payload(site, fault, node_id=self.node_id,
                                  **fields)
        self.loop.call_soon_threadsafe(
            F.emit_raw, spawn_task, self._gcs, payload)

    async def _probe_chaos_rev(self) -> None:
        """Out-of-band plan-revision check while heartbeats are being
        chaos-dropped — the escape hatch that keeps disarm reachable."""
        try:
            reply = await self._gcs.call("chaos_status", {}, timeout=10.0)
        except Exception:  # noqa: BLE001 — next probe retries
            return
        rev = reply.get("rev")
        if rev is not None and rev != self._chaos_seen_rev:
            await self._sync_chaos(rev, reply.get("armed", True))

    async def _sync_chaos(self, rev: int, armed: bool = True) -> None:
        """The GCS announced a new chaos-plan revision: fetch the plan via
        the chaos-exempt ``chaos_status`` RPC (a live rpc.drop plan must
        not block its own update), arm/disarm this process, and forward
        to live workers (new workers get the plan via RT_CHAOS_PLAN_JSON
        at spawn). ``armed=False`` (from the heartbeat reply) skips the
        fetch so a DISARM always lands."""
        plan = None
        if armed:
            try:
                reply = await self._gcs.call("chaos_status", {},
                                             timeout=10.0)
            except Exception:  # noqa: BLE001 — next heartbeat retries
                return
            plan = reply.get("plan")
            if plan is not None:
                try:
                    C.arm(plan, rev=rev)
                except Exception:  # noqa: BLE001 — malformed: stay safe
                    plan = None
        if plan is None:
            C.disarm()
        self._chaos_seen_rev = rev
        for entry in list(self._workers.values()):
            if entry.client is None or entry.proc.poll() is not None:
                continue
            try:
                await entry.client.call(
                    "chaos_arm", {"plan": plan, "rev": rev}, timeout=5.0)
            except Exception:  # noqa: BLE001 — worker mid-death or busy
                continue

    # ---- GCS-outage degraded mode ------------------------------------------
    # Only TRANSPORT failures mean "the GCS is unreachable"; an
    # application-level RpcError is a healthy GCS rejecting this payload —
    # deferring it would poison the replay queue (same payload, same
    # rejection, forever) and wedge the raylet in degraded mode.
    _TRANSIENT_GCS_ERRORS = (OSError, asyncio.TimeoutError)

    def _is_transient(self, e: BaseException) -> bool:
        return isinstance(e, (ConnectionLost,) + self._TRANSIENT_GCS_ERRORS)

    def _defer(self, method: str, payload: Dict) -> None:
        if len(self._deferred_gcs) == self._deferred_gcs.maxlen:
            # overflow evicts the oldest entry — COUNTED, never silent;
            # the resync path repairs with a full location republish
            self._deferred_dropped += 1
        self._deferred_gcs.append((method, payload))

    async def _gcs_publish(self, method: str, payload: Dict) -> None:
        """Bookkeeping updates (object locations, death reports) that must
        not fail LOCAL execution when the GCS is unreachable: in degraded
        mode they defer immediately (no per-call reconnect stall) and
        replay in order once the heartbeat loop sees the GCS again.
        Application errors propagate to the caller as before."""
        if self._degraded_since is not None:
            self._defer(method, payload)
            return
        try:
            await self._gcs.call(method, payload, timeout=10.0)
        except Exception as e:  # noqa: BLE001
            if not self._is_transient(e):
                raise
            if self._degraded_since is None:
                self._degraded_since = time.monotonic()
            self._defer(method, payload)

    async def _flush_deferred_guarded(self) -> None:
        try:
            await self._flush_deferred()
        finally:
            self._flushing = False

    async def _flush_deferred(self) -> None:
        """Replay deferred bookkeeping after a GCS outage; exits degraded
        mode only when the whole backlog lands. A transport failure means
        the GCS bounced again — stay degraded, keep the rest queued; an
        application rejection drops THAT entry (a poisoned payload must
        not head-of-line-block the backlog forever)."""
        n = len(self._deferred_gcs)
        while self._deferred_gcs:
            method, payload = self._deferred_gcs.popleft()
            try:
                await self._gcs.call(method, payload, timeout=10.0)
            except Exception as e:  # noqa: BLE001
                if self._is_transient(e):  # still (or again) down
                    self._deferred_gcs.appendleft((method, payload))
                    return
                continue  # rejected by a healthy GCS: drop, keep flushing
        outage_s = time.monotonic() - (self._degraded_since
                                       or time.monotonic())
        self._degraded_since = None
        dropped = self._deferred_dropped
        self._deferred_dropped = 0
        if dropped:
            # the deque overflowed during the outage: some location
            # updates are gone — repair wholesale by republishing every
            # object this node still serves (idempotent adds)
            spawn_task(self._reconcile_after_resurrection())
        self._failure_event(
            F.UNKNOWN,
            f"raylet ran degraded for {outage_s:.1f}s during a GCS "
            f"outage; resynced {n} deferred update(s)"
            + (f", {dropped} overflowed (full location republish "
               f"triggered)" if dropped else ""),
            origin="recovery")

    # ---- worker pool --------------------------------------------------------
    def _spawn_worker(self, key: Tuple, chips: List[int],
                      runtime_env: Optional[Dict] = None,
                      python_exe: Optional[str] = None) -> _WorkerEntry:
        worker_id = os.urandom(8).hex()
        env = dict(os.environ)
        env["RT_WORKER_ID"] = worker_id
        env["RT_RAYLET_ADDR"] = self.server.address
        env["RT_GCS_ADDR"] = self.gcs_address
        env["RT_NODE_ID"] = self.node_id
        env["RT_SESSION_NAME"] = self.session_name
        env["RT_CONFIG_JSON"] = get_config().to_json()
        # user prints must reach the log file (and the driver echo) promptly,
        # not sit in a block buffer until the worker exits
        env["PYTHONUNBUFFERED"] = "1"
        if runtime_env:
            env["RT_RUNTIME_ENV_JSON"] = json.dumps(runtime_env)
        if chips:
            env[get_config().tpu_visible_chips_env] = ",".join(map(str, chips))
        if C.armed():
            # new workers join the tortured cluster armed from birth (live
            # workers got the plan via the chaos_arm RPC)
            env["RT_CHAOS_PLAN_JSON"] = C.plan_json()
        else:
            env.pop("RT_CHAOS_PLAN_JSON", None)
        log_dir = os.path.join(get_config().session_dir_root,
                               self.session_name, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_file = open(os.path.join(log_dir, f"worker-{worker_id}.log"), "wb")
        proc = subprocess.Popen(
            [python_exe or sys.executable, "-m",
             "ray_tpu.cluster.worker_main"],
            env=env, stdout=log_file, stderr=subprocess.STDOUT)
        log_file.close()
        entry = _WorkerEntry(worker_id, proc, key, self.loop)
        self._workers[worker_id] = entry
        return entry

    async def rpc_worker_ready(self, p):
        entry = self._workers.get(p["worker_id"])
        if entry is None:
            return {"ok": False}
        entry.address = p["address"]
        entry.client = await self._pool.get(p["address"])
        if not entry.ready.done():
            entry.ready.set_result(True)
        if self._chaos_seen_rev > 0 or C.armed():
            # a worker spawned just before a plan-rev change registered too
            # late for _sync_chaos's forward and too early for the spawn
            # env — hand it the CURRENT state so no worker runs stale
            pj = C.plan_json()
            spawn_task(self._call_quietly(entry.client, "chaos_arm", {
                "plan": json.loads(pj) if pj else None,
                "rev": C.current_rev()}))
        return {"ok": True, "node_id": self.node_id}

    async def _call_quietly(self, client, method: str, payload: Dict) -> None:
        try:
            await client.call(method, payload, timeout=5.0)
        except Exception:  # noqa: BLE001 — best-effort side channel
            pass

    async def _get_worker(self, key: Tuple, chips: List[int],
                          runtime_env: Optional[Dict] = None
                          ) -> Tuple[_WorkerEntry, str]:
        """Returns ``(worker, source)`` with source "warm" (pool hit) or
        "spawn" (fresh process) — the phase tracer's worker_acquire tag.

        Idle worker or a new spawn — with spawn THROTTLING: at most
        ``_spawn_slots`` worker processes boot concurrently. A burst of N
        first-touch tasks must not fork N interpreters at once — on a
        small host the spawn stampede thrashes every boot past the startup
        timeout, and each timed-out waiter used to ABANDON its live
        process and retry, forking more (discovered by `rt
        scale-envelope`). Waiters poll the idle pool while throttled, so
        a released worker is picked up ahead of any new spawn; a spawn
        that still times out is KILLED, not leaked."""
        while True:
            idle = self._idle.get(key)
            while idle:
                entry = idle.pop()
                if entry.proc.poll() is None:
                    entry.idle_since = None
                    return entry, "warm"
                self._workers.pop(entry.worker_id, None)
            if self._spawn_slots > 0:
                break
            await asyncio.sleep(0.05)
        self._spawn_slots -= 1
        try:
            python_exe = None
            if runtime_env and runtime_env.get("venv"):
                # hermetic env: materialize the virtualenv OFF the raylet
                # loop and boot the worker with its interpreter (reference:
                # the agent's conda/container setup swapping
                # context.py_executable)
                # rt: lint-allow(hot-path) heavy venv machinery on the
                # cold per-env boot path, not per-dispatch
                from ray_tpu.runtime_env.runtime_env import ensure_venv

                cache_root = os.path.join(get_config().session_dir_root,
                                          self.session_name, "runtime_env")
                # setup stays bounded like the worker-side pip path; on
                # timeout the task fails (the executor thread finishes in
                # the background and the venv, if it completes, is cached)
                python_exe = await asyncio.wait_for(
                    self.loop.run_in_executor(
                        None, ensure_venv, runtime_env, cache_root),
                    get_config().runtime_env_setup_timeout_s)
            entry = self._spawn_worker(key, chips, runtime_env, python_exe)
            cfg = get_config()
            timeout = cfg.process_startup_timeout_s + (
                cfg.runtime_env_setup_timeout_s if runtime_env else 0)
            try:
                await asyncio.wait_for(entry.ready, timeout)
            except asyncio.TimeoutError:
                entry.proc.kill()
                self._workers.pop(entry.worker_id, None)
                raise
            return entry, "spawn"
        finally:
            self._spawn_slots += 1

    def _release_worker(self, entry: _WorkerEntry) -> None:
        entry.busy = False
        entry.current_task = None
        if entry.proc.poll() is None and not entry.is_actor_worker:
            entry.idle_since = time.monotonic()
            self._idle.setdefault(entry.key, []).append(entry)

    _UPLOAD_TTL_S = 600.0

    async def _reap_loop(self) -> None:
        """Detect dead worker processes (reference: worker death via local
        socket disconnect); also purges client uploads abandoned mid-stream
        (dead client) so unsealed store allocations can't pile up."""
        self._last_pin_purge = 0.0
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            if now - self._last_pin_purge > 5.0:
                # get-pin TTL enforcement on a timer: leaked pins from
                # crashed getters must expire even when no spill pass or
                # pin burst ever runs (they would otherwise exempt their
                # objects from eviction forever)
                self._last_pin_purge = now
                self._purge_stale_pins(now)
            for oid_hex, (_, t0) in list(self._client_uploads.items()):
                if now - t0 > self._UPLOAD_TTL_S:
                    self._client_uploads.pop(oid_hex, None)
                    try:
                        self.store.delete(ObjectID.from_hex(oid_hex))
                    except Exception:  # noqa: BLE001
                        pass
            # idle-worker reaping (reference: the worker pool's idle
            # killing): pooled workers beyond the soft limit that sat
            # idle past the TTL are retired oldest-first — bounds process
            # growth when jobs cycle through many runtime envs
            cfg = get_config()
            soft = cfg.num_workers_soft_limit or max(
                1, int(self.node.total.get(CPU) or 1))
            all_idle = sorted(
                (e for lst in self._idle.values() for e in lst
                 if e.idle_since is not None),
                key=lambda e: e.idle_since)
            surplus = len(all_idle) - soft
            for entry in all_idle[:max(0, surplus)]:
                if now - entry.idle_since <= cfg.idle_worker_ttl_s:
                    break  # oldest within TTL -> all newer ones are too
                self._idle.get(entry.key, []).remove(entry)
                self._workers.pop(entry.worker_id, None)
                try:
                    entry.proc.terminate()
                except Exception:  # noqa: BLE001 — already gone
                    pass

            # warm-pool prestart (reference: worker_pool.h PrestartWorkers):
            # keep the configured floor of plain workers idle so the next
            # cold dispatch or actor creation finds a live interpreter.
            # Bounded per tick so a floor bump can't stampede the host.
            if cfg.worker_prestart_floor > 0 and not self._stopped:
                warm_idle = sum(
                    1 for e in self._idle.get(_WARM_KEY, ())
                    if e.proc.poll() is None)
                # floor capped by the idle soft limit: a floor above it
                # would fight the surplus reaper above in a perpetual
                # boot/retire churn loop on an otherwise idle node
                floor = min(cfg.worker_prestart_floor, soft)
                want = floor - warm_idle - self._prestarting
                for _ in range(min(max(0, want), 2)):
                    self._prestarting += 1
                    spawn_task(self._prestart_worker())

            for entry in list(self._workers.values()):
                if entry.proc.poll() is not None:
                    self._workers.pop(entry.worker_id, None)
                    if entry.is_actor_worker and entry.actor_id:
                        getattr(entry, "_pool", self.node).release(
                            ResourceSet(entry_spec_resources(entry)), entry.assignment)
                        if entry.oom_killed:
                            cause = F.cause_dict(
                                F.OOM_KILL,
                                "killed by the memory monitor (node over "
                                "memory_usage_threshold)",
                                node_id=self.node_id,
                                worker_id=entry.worker_id)
                        else:
                            cause = F.cause_dict(
                                F.WORKER_CRASH,
                                f"worker exited with code "
                                f"{entry.proc.returncode}",
                                node_id=self.node_id,
                                worker_id=entry.worker_id,
                                exit_code=entry.proc.returncode)
                        # degraded-aware: a dead actor's report must not
                        # kill the reap loop while the GCS is down — it
                        # defers and replays on resync (the restart budget
                        # is honored late rather than never); an outright
                        # GCS rejection is swallowed too (retrying the
                        # same report cannot help, and the loop must live)
                        try:
                            await self._gcs_publish("actor_update", {
                                "actor_id": entry.actor_id, "state": "DEAD",
                                "node_id": self.node_id,
                                "reason": cause["message"], "cause": cause})
                        except Exception:  # noqa: BLE001
                            pass
                        entry.is_actor_worker = False

    async def _prestart_worker(self) -> None:
        """Boot one warm-pool worker and release it into the idle pool.
        Failures are silent — the floor check next tick tries again.
        Prestart never outbids task-driven boots for spawn slots: when
        the throttle is saturated it skips (worsening a boot stampede to
        warm the pool defeats both)."""
        try:
            if self._spawn_slots <= 0:
                return
            self._spawn_slots -= 1
            try:
                entry = self._spawn_worker(_WARM_KEY, [], None)
                try:
                    await asyncio.wait_for(
                        entry.ready, get_config().process_startup_timeout_s)
                except asyncio.TimeoutError:
                    entry.proc.kill()
                    self._workers.pop(entry.worker_id, None)
                    return
                self._sched_stats["prestarted"] += 1
                self._release_worker(entry)
                self._dispatch_event.set()
            finally:
                self._spawn_slots += 1
        except Exception:  # noqa: BLE001 — next reap tick retries
            pass
        finally:
            self._prestarting -= 1

    async def _reattach_after_gcs_restart(self) -> None:
        """Re-publish live actor workers to a restarted GCS, then run the
        standard reconciliation (object locations + stale-state cleanup)."""
        for entry in list(self._workers.values()):
            if not (entry.is_actor_worker and entry.actor_id
                    and entry.address):
                continue
            try:
                await self._gcs.call("actor_update", {
                    "actor_id": entry.actor_id, "state": "ALIVE",
                    "address": entry.address, "node_id": self.node_id})
            except Exception:  # noqa: BLE001 — next heartbeat retries
                return
        await self._reconcile_after_resurrection()

    async def _reconcile_after_resurrection(self) -> None:
        """While this node was (spuriously) dead, the GCS dropped our object
        locations and may have restarted our actors elsewhere / rescheduled
        our PG bundles. Re-publish every object this node can still serve
        (shm AND spilled — spill files serve chunks too), kill local actor
        workers the GCS no longer maps to this node (duplicate
        side-effecting copies otherwise), and release bundle reservations we
        no longer own. Failures are per-item; a republish that dies midway
        is retried wholesale by the next resurrection or get-path repair."""
        oids = {o.hex() for o in self.store.list_objects()}
        oids.update(h for h, m in self._object_meta.items()
                    if m.get("spilled"))
        for oid_hex in oids:
            try:
                await self._gcs.call("add_object_location", {
                    "oid": oid_hex, "node_id": self.node_id})
            except Exception:  # noqa: BLE001 — transient; keep going
                continue
        for entry in list(self._workers.values()):
            if not entry.is_actor_worker or not entry.actor_id:
                continue
            try:
                reply = await self._gcs.call(
                    "get_actor_info", {"actor_id": entry.actor_id})
                info = reply.get("info")
            except Exception:  # noqa: BLE001 — next heartbeat retries
                continue
            if info is None or info.get("node_id") != self.node_id \
                    or info.get("state") == "DEAD":
                entry.is_actor_worker = False  # suppress the DEAD re-report
                entry.actor_id = None
                getattr(entry, "_pool", self.node).release(
                    ResourceSet(entry_spec_resources(entry)),
                    entry.assignment)
                self._terminate_worker(entry)
                self._dispatch_event.set()
        for (pg_id, idx), bundle in list(self._bundles.items()):
            try:
                reply = await self._gcs.call(
                    "get_placement_group", {"pg_id": pg_id})
            except Exception:  # noqa: BLE001
                continue
            info = reply.get("info") or reply
            nodes = info.get("bundle_nodes") or []
            if (info.get("state") == "REMOVED"
                    or idx >= len(nodes) or nodes[idx] != self.node_id):
                self._bundles.pop((pg_id, idx), None)
                self.node.release(bundle.node_req, bundle.node_assignment)
                self._dispatch_event.set()

    def _terminate_worker(self, entry: _WorkerEntry,
                          grace_s: float = 5.0) -> None:
        """SIGTERM now, SIGKILL if still alive after the grace period. The
        entry STAYS in ``_workers`` so the reap loop's ``poll()`` collects
        the child (popping immediately would leak a zombie — nothing would
        ever wait() it)."""
        try:
            entry.proc.terminate()
        except ProcessLookupError:
            return

        async def _escalate():
            await asyncio.sleep(grace_s)
            if entry.proc.poll() is None:
                try:
                    entry.proc.kill()
                except ProcessLookupError:
                    pass

        spawn_task(_escalate())

    # injectable for tests (fake pressure without allocating gigabytes);
    # instance-level plain callable, so no descriptor binding applies
    _memory_info_fn = None

    async def _memory_monitor_loop(self) -> None:
        """OOM prevention (reference: ``common/memory_monitor.h`` polling +
        ``raylet/worker_killing_policy.cc``): when node memory use crosses
        ``memory_usage_threshold``, kill one worker — retriable task workers
        first, largest RSS — so the kernel OOM-killer never takes down the
        raylet or an arbitrary process."""
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            try:
                f = C.maybe_fire("oom.pressure")
                if f is not None:
                    # synthetic memory pressure: report the node at `value`
                    # (fraction) so the monitor's kill path runs for real
                    self._chaos_stamp("oom.pressure", f)
                    info = {"total": 1000,
                            "used": int(1000 * float(f.get("value", 0.99)))}
                else:
                    # per-tick lookup: tests inject a fake probe on the
                    # instance
                    info = (self._memory_info_fn or _native.memory_info)()
                total, used = info.get("total", -1), info.get("used", -1)
                if total <= 0 or used < 0:
                    continue
                if used / total < cfg.memory_usage_threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                victim.oom_killed = True
                victim_rss = _native.process_rss(victim.proc.pid)
                try:
                    victim.proc.kill()
                except ProcessLookupError:
                    pass
                self._record_oom_kill(victim, victim_rss,
                                      {"total": total, "used": used})
            except Exception:  # noqa: BLE001 — monitor must never die
                pass

    def _record_oom_kill(self, victim: _WorkerEntry, victim_rss: int,
                         node_memory: Dict[str, int]) -> None:
        """OOM post-mortem: stamp a GCS ``oom_kill`` event carrying the node
        memory state, the victim (RSS, role, running task/actor) and the
        top-10 largest live store objects — what `rt memory --oom` replays.
        The kill itself already happened; everything here is best-effort."""
        self._mem_stats["oom_kills"] += 1
        if self._telemetry:
            try:
                self._telemetry_metrics()["oom_kills"].inc(
                    1.0, {"node_id": self.node_id})
            except Exception:  # noqa: BLE001
                pass
        self._failure_event(
            F.OOM_KILL,
            f"memory monitor killed worker {victim.worker_id[:8]} "
            f"(rss {victim_rss}, node at "
            f"{node_memory.get('used', 0)}/{node_memory.get('total', 0)})",
            worker_id=victim.worker_id, actor_id=victim.actor_id,
            task=victim.current_task)
        top = sorted(((oid, m) for oid, m in self._object_meta.items()),
                     key=lambda kv: -kv[1]["size"])[:10]
        self._mem_event(
            "oom_kill",
            node_memory=dict(node_memory),
            victim={
                "worker_id": victim.worker_id, "pid": victim.proc.pid,
                "rss": victim_rss,
                "role": "actor" if victim.is_actor_worker else "worker",
                "actor_id": victim.actor_id,
                "task": victim.current_task, "busy": victim.busy},
            top_objects=[{"oid": oid, "size": m["size"],
                          "state": "spilled" if m.get("spilled")
                          else "in_memory"} for oid, m in top])

    def _pick_oom_victim(self) -> Optional[_WorkerEntry]:
        idle_workers, task_workers, actor_workers = [], [], []
        for e in self._workers.values():
            if e.proc.poll() is not None or e.oom_killed:
                continue
            if e.is_actor_worker:
                actor_workers.append(e)
            elif e.busy:
                task_workers.append(e)
            else:
                idle_workers.append(e)
        # Cheapest kill first (reference worker_killing_policy.cc prefers
        # the lowest-cost victim): an idle pooled worker loses no work yet
        # can hold large RSS from its previous task; then busy task workers
        # (retriable by policy, largest RSS frees the most); actors only as
        # a last resort — their death is user-visible (restart or
        # ActorDiedError).
        for group in (idle_workers, task_workers, actor_workers):
            if not group:
                continue
            by_pid = {e.proc.pid: e for e in group}
            ranked = _native.process_memory(list(by_pid))
            if ranked:
                return by_pid[ranked[0][0]]
        return None

    # ---- worker log plumbing (reference: _private/log_monitor.py) ----------
    # The raylet tails every worker log file and keeps a bounded ring of
    # recent lines; drivers long-poll it and echo lines to their stderr
    # (``log_to_driver``). File offsets persist across the pump's life so
    # each line is forwarded once.

    @staticmethod
    def _scan_worker_logs(log_dir: str, offsets: Dict[str, int]
                          ) -> List[Tuple[str, List[str]]]:
        """One tail pass over the worker log files (executor thread —
        listdir/stat/open/read never touch the event loop). Mutates
        ``offsets`` in place; returns [(worker_id, lines), ...]."""
        out: List[Tuple[str, List[str]]] = []
        try:
            names = os.listdir(log_dir)
        except FileNotFoundError:
            return out
        for name in names:
            if not name.startswith("worker-"):
                continue
            path = os.path.join(log_dir, name)
            off = offsets.get(name, 0)
            try:
                size = os.path.getsize(path)
                if size <= off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(256 * 1024)
                # forward whole lines; keep a partial tail for next
                # tick — unless the window is FULL with no newline (one
                # giant line): forward it truncated and advance, or the
                # pump would re-read the same window forever
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    if len(chunk) < 256 * 1024:
                        continue  # incomplete line still being written
                    cut = len(chunk)
                offsets[name] = off + cut + (0 if cut == len(chunk)
                                             else 1)
                wid = name[len("worker-"):-len(".log")]
                lines = chunk[:cut].decode(errors="replace").splitlines()
                if lines:
                    out.append((wid, lines))
            except OSError:
                continue
        return out

    async def _log_pump_loop(self) -> None:
        offsets: Dict[str, int] = {}
        log_dir = os.path.join(get_config().session_dir_root,
                               self.session_name, "logs")
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(0.3)
            # the tail reads run on the spill/file-IO pool; only the ring
            # append + waiter wakeup touch the loop
            scanned = await loop.run_in_executor(
                self._spill_exec, self._scan_worker_logs, log_dir, offsets)
            new_any = False
            for wid, lines in scanned:
                wentry = self._workers.get(wid)
                job = wentry.job_id if wentry is not None else None
                for line in lines:
                    self._log_seq += 1
                    self._log_buf.append(
                        {"seq": self._log_seq, "worker_id": wid,
                         "job_id": job, "line": line})
                    new_any = True
            if new_any:
                self._log_event.set()
                self._log_event = asyncio.Event()

    async def rpc_poll_logs(self, p):
        """Long-poll new worker log lines after ``seq`` (0 = from now)."""
        buf = self._log_buf
        after = p.get("after")
        if after is None:
            return {"seq": self._log_seq, "entries": []}
        job = p.get("job_id")

        def wanted(e):
            # route lines to their owning driver (reference: log_monitor
            # per-job routing); untagged lines (worker idle / pre-dispatch
            # prints) broadcast to every poller
            return (e["seq"] > after
                    and (job is None or e.get("job_id") in (None, job)))

        entries = [e for e in buf if wanted(e)]
        if not entries:
            try:
                await asyncio.wait_for(self._log_event.wait(),
                                       p.get("timeout", 10.0))
            except asyncio.TimeoutError:
                pass
            entries = [e for e in buf if wanted(e)]
        # seq must advance past FILTERED entries too, or the poller re-scans
        newest = max((e["seq"] for e in buf), default=after)
        return {"seq": max(newest, after), "entries": entries}

    async def _on_peer_disconnect(self, peer_id: str) -> None:
        pass

    # ---- task submission / dispatch ----------------------------------------
    async def rpc_submit_task(self, p):
        """Held open until the task completes; reply carries results meta.

        Duplicate submissions of the same task_id (owner retried after a
        dropped connection) join the in-flight execution or get the cached
        successful reply — the task body never runs twice for a transport
        failure. A genuine execution failure is NOT cached, so a retry after
        ``worker_crashed`` re-executes as intended.
        """
        task_id = p["task_id"]
        cached = self._replies.get(task_id)
        if cached is not None:
            if not p.get("reconstruct"):
                return cached
            # lineage reconstruction MUST re-execute: the cached reply's
            # plasma objects are exactly what was lost
            self._replies.pop(task_id, None)
        existing = self._task_futures.get(task_id)
        if existing is not None:
            return await asyncio.shield(existing)
        # Admission control (before any state is created for the task): a
        # scheduling class at its queue bound bounces the submit with a
        # backpressure reply instead of absorbing an unbounded producer —
        # the owner blocks-with-backoff (default) or fails fast
        # (on_overload="fail"); either way the raylet never wedges under a
        # runaway submit loop.
        cfg = get_config()
        skey = _SchedQueues.class_key(p)
        if (cfg.max_queued_per_class > 0
                and self._squeue.depth(skey) >= cfg.max_queued_per_class):
            self._sched_stats["backpressure"] += 1
            if self._telemetry:
                try:
                    self._telemetry_metrics()["backpressure"].inc(
                        1.0, {"node_id": self.node_id})
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
            return {"error": "backpressure",
                    "queue_depth": self._squeue.depth(skey),
                    "limit": cfg.max_queued_per_class,
                    "retry_after_s": cfg.backpressure_retry_base_s}
        fut = asyncio.get_running_loop().create_future()
        self._task_futures[task_id] = fut

        def _on_done(f, _tid=task_id):
            # Runs even if this handler's connection dropped mid-await.
            self._task_futures.pop(_tid, None)
            if not f.cancelled() and f.exception() is None:
                reply = f.result()
                if not reply.get("error"):
                    self._replies[_tid] = reply
                    while len(self._replies) > 4096:
                        self._replies.pop(next(iter(self._replies)))

        fut.add_done_callback(_on_done)
        # Locally-infeasible tasks QUEUE here too (not fail): the spillback
        # pass forwards them when another node has capacity, and until then
        # they ride the heartbeat's queued_demands — the signal the
        # autoscaler provisions against (reference: infeasible tasks stay
        # pending and drive resource_demand_scheduler).
        item = {"payload": p, "future": fut, "skey": skey,
                "label": _SchedQueues.class_label(skey),
                "t": time.monotonic(), "spilling": False}
        # separate stamp: spillback backoff resets item["t"], but the
        # span's queue_wait, the per-class oldest_wait_s and the wait-p99
        # samples must all cover the FULL local wait (a class whose head
        # keeps failing spillback is starving, not freshly enqueued)
        item["t_enq"] = item["t"]
        # deadline budget: end-to-end staleness bound measured from local
        # enqueue (clocks don't cross processes); an expired item is shed
        # by the dispatch head check or the heartbeat sweep
        if p.get("deadline_s"):
            item["expires"] = item["t"] + float(p["deadline_s"])
        self._squeue.push(item)
        self._task_event(task_id, p.get("fn_name"), "PENDING",
                         trace=p.get("trace"))
        self._dispatch_event.set()
        return await asyncio.shield(fut)

    def _local_features(self, skey=None, payload=None) -> Dict[str, Any]:
        """This node's feature vector for a placement receipt's candidate
        set: the local half of what rpc_route_task's candidates carry for
        peers (queue state, warm pool, resource headroom) plus the one
        feature only the origin raylet knows — how many bytes of the
        task's args are already plasma-resident here (the locality input a
        learned placement policy would weigh)."""
        out: Dict[str, Any] = {
            "node_id": self.node_id,
            "queue_depth": len(self._squeue),
            "warm_idle": len(self._idle.get(_WARM_KEY, ())),
            "headroom": self.node.available.to_dict(),
        }
        if skey is not None:
            out["class_depth"] = self._squeue.depth(skey)
            head = self._squeue.head(skey)
            out["oldest_wait_s"] = round(max(
                0.0, time.monotonic() - head["t_enq"]), 3) if head else 0.0
        if payload is not None:
            locality = 0
            entries = list(payload.get("args") or ())
            entries += list((payload.get("kwargs") or {}).values())
            for ent in entries:
                try:
                    kind, val = ent
                    if kind != "ref":
                        continue
                    oid = val[0].hex()
                    if oid in self._local_objects:
                        meta = self._object_meta.get(oid) or {}
                        if not meta.get("spilled"):
                            locality += int(meta.get("size", 0))
                except Exception:  # noqa: BLE001 — telemetry only
                    continue
            out["locality_bytes"] = locality
        return out

    def _placement_event(self, rec: Dict[str, Any]) -> None:
        """Placement decision receipt (kind, chosen node, reason, candidate
        features) bound for the GCS ``placement_events`` store. Rides the
        SAME coalesced ``task_events`` channel as state events — one
        batched drain, no second RPC path — and is routed to its own store
        on arrival. Observability only: never blocks the dispatch path."""
        msg = {"task_id": rec.get("task_id"), "placement": rec}
        if get_config().task_event_flush_s <= 0:
            async def _send(m=rec):
                try:
                    await self._gcs.call("placement_event", m)
                except Exception:  # noqa: BLE001 — observability only
                    pass

            spawn_task(_send())
            return
        self._task_event_buf.append(msg)
        if not self._task_event_flushing:
            self._task_event_flushing = True
            spawn_task(self._flush_task_events())

    def _task_event(self, task_id: str, name, state: str,
                    trace: "Optional[Dict]" = None,
                    phases: "Optional[Dict]" = None,
                    worker_source: Optional[str] = None,
                    spill_hop: "Optional[Dict]" = None) -> None:
        """Buffered state event to the GCS task store (reference:
        TaskEventBuffer -> GcsTaskManager); observability only, never blocks
        or fails the task path. Events COALESCE into one batched
        ``task_events`` RPC per flush window instead of one round-trip per
        state change — at 3 states per task the unbatched form dominated
        the submit hot path's GCS chatter. A single in-flight flusher
        drains the buffer FIFO, so per-task state order is preserved.
        ``trace`` carries the span context when the submitter had tracing
        enabled; ``phases`` the per-phase latency breakdown this raylet
        measured for a traced task."""
        msg = {"task_id": task_id, "name": name, "state": state,
               "node_id": self.node_id}
        if state is not None:
            # client-side stamp (the driver's phase partials already do
            # this): batching would otherwise collapse a short task's
            # PENDING/RUNNING/FINISHED onto one server arrival time and
            # zero its timeline lane
            msg["times"] = {state: time.time()}
        if trace is not None:
            msg["trace"] = trace
        if phases:
            msg["phases"] = phases
        if worker_source is not None:
            msg["worker_source"] = worker_source
        if spill_hop is not None:
            msg["spill_hop"] = spill_hop
        if get_config().task_event_flush_s <= 0:
            # batching off: ship each event on its own fire-and-forget RPC
            async def _send(m=msg):
                try:
                    await self._gcs.call("task_event", m)
                except Exception:  # noqa: BLE001 — observability only
                    pass

            spawn_task(_send())
            return
        self._task_event_buf.append(msg)
        if state in ("FINISHED", "FAILED"):
            # terminal states flush NOW (whole buffer, order kept): the
            # owner's reply races this event to the GCS, and consumers
            # (tracing polls, the driver's phases partial) must find the
            # terminal event the moment the reply is visible — only the
            # PENDING/RUNNING chatter rides the coalescing window
            self._task_event_kick.set()
        if not self._task_event_flushing:
            self._task_event_flushing = True
            spawn_task(self._flush_task_events())

    async def _flush_task_events(self) -> None:
        try:
            while self._task_event_buf:
                if not self._task_event_kick.is_set():
                    try:
                        await asyncio.wait_for(
                            self._task_event_kick.wait(),
                            get_config().task_event_flush_s)
                    except asyncio.TimeoutError:
                        pass
                self._task_event_kick = asyncio.Event()
                while self._task_event_buf:
                    batch = []
                    while self._task_event_buf and len(batch) < 512:
                        batch.append(self._task_event_buf.popleft())
                    try:
                        await self._gcs.call("task_events",
                                             {"events": batch})
                    except Exception:  # noqa: BLE001 — observability only:
                        # drop this batch rather than loop hot against a
                        # down GCS; the finally-side retrigger retries the
                        # REST of the buffer after a pause (terminal events
                        # of a job's last tasks must not strand forever)
                        return
        finally:
            self._task_event_flushing = False
            if self._task_event_buf:
                spawn_task(self._reflush_task_events(1.0))
            elif self._task_event_kick.is_set():
                # a terminal event that landed mid-drain (and was drained)
                # set the kick; left set, the next flusher would skip the
                # coalescing window and ship 1-event batches
                self._task_event_kick = asyncio.Event()

    async def _reflush_task_events(self, delay_s: float) -> None:
        await asyncio.sleep(delay_s)
        if self._task_event_buf and not self._task_event_flushing:
            self._task_event_flushing = True
            await self._flush_task_events()

    async def _try_spillback(self, item) -> None:
        """Forward a queued-but-waiting task to a node with free capacity.
        The task stays in our queue (flagged) until a target accepts it, so
        local dispatch can still claim it if the attempt finds nothing."""
        payload = dict(item["payload"])
        payload["spill_count"] = payload.get("spill_count", 0) + 1
        # Acyclic hop chain: a spilled task must never return to a node it
        # already visited. Two loaded nodes ping-ponging one task would each
        # hit the peer's duplicate-task_id guard and JOIN the other's
        # held-open original future while the task sits in NEITHER queue —
        # a distributed deadlock (both futures wait on each other forever).
        path = [n for n in (item["payload"].get("spill_path") or ())
                if n != self.node_id]
        path.append(self.node_id)
        payload["spill_path"] = path
        payload.pop("spillback_hint", None)
        try:
            route = await self._gcs.call("route_task", {
                "resources": payload["resources"],
                "strategy": payload.get("strategy"),
                "require_available": True, "exclude": list(path),
                # placement receipts: ship the considered candidates'
                # feature vectors back so the hop record is truthful
                "features": True})
        except Exception:
            route = {}
        if not route.get("address"):
            item["spilling"] = False
            item["t"] = time.monotonic()  # back off before the next attempt
            return
        if not self._squeue.remove(item):
            item["spilling"] = False
            return  # local dispatch already claimed it
        # hop hand-off time, captured BEFORE the forward: the forward's
        # submit_task is held open until the task COMPLETES remotely, so
        # measuring after the call would fold the whole remote execution
        # into the hop. The spillback phase = local wait + routing overhead
        # up to hand-off (the remote raylet owns queue_wait onward).
        hop_s = time.monotonic() - item.get("t_enq", item["t"])
        try:
            client = await self._pool.get(route["address"])
            reply = await client.call("submit_task", payload)
        except Exception:
            # Target died between the GCS view and the forward: the task is
            # still locally runnable — requeue it rather than failing the
            # caller (same task_id, so a remote execution that did land
            # dedups at that raylet; tasks are retry-idempotent by contract).
            item["spilling"] = False
            item["t"] = time.monotonic()
            self._squeue.push(item)
            self._dispatch_event.set()
            return
        if isinstance(reply, dict) and reply.get("error") == "backpressure":
            # the peer's admission bound is its own: this task was already
            # admitted HERE — requeue locally instead of propagating a
            # bounce the owner never earned (fail-fast callers would raise
            # BackpressureError for a node they never overloaded).
            # Deliberately NO placement receipt on this requeue (nor on the
            # no-target / forward-failure paths above): the task did not
            # move, and stamping a bounced attempt would double-count the
            # eventual successful hop.
            item["spilling"] = False
            item["t"] = time.monotonic()
            self._squeue.push(item)
            self._dispatch_event.set()
            return
        # the task moved: THE one spillback stamp site. reason carries why
        # the local node was rejected (_maybe_spill_class stamped it on the
        # item); candidates = this node's features + the GCS's view of the
        # peers it considered.
        reason = item.get("spill_reason") or "queue_bound"
        self._placement_event({
            "kind": "spillback",
            "task_id": payload.get("task_id"),
            "name": payload.get("fn_name"),
            "from_node": self.node_id,
            "node_id": route.get("node_id"),
            "reason": reason,
            "hops": payload["spill_count"],
            "path": path + [route.get("node_id")],
            "candidates": ([self._local_features(item.get("skey"),
                                                 payload)]
                           + (route.get("candidates") or [])),
        })
        if payload.get("trace") is not None:
            # the hop joins the task's phase breakdown: a phases-only
            # partial merging into the event the executing node owns
            self._task_event(
                payload["task_id"], payload.get("fn_name"), None,
                phases={"spillback": hop_s},
                spill_hop={"from": self.node_id,
                           "to": route.get("node_id"),
                           "reason": reason})
        fut = item["future"]
        if not fut.done():
            fut.set_result(reply)

    async def _dispatch_loop(self) -> None:
        while True:
            await self._dispatch_event.wait()
            self._dispatch_event.clear()
            self._dispatch_pass()

    def _dispatch_pass(self) -> None:
        """One fairness sweep over the per-class queues (reference:
        ``LocalTaskManager::ScheduleAndDispatchTasks`` over per-class
        deques): classes take turns claiming resources — one dispatch per
        class per turn, FIFO within a class, and a class that dispatched
        rotates to the back. A 5k-deep bulk class therefore costs a 1-task
        probe class exactly one dispatch slot, not the whole backlog.
        Sweeps repeat until a full rotation makes no progress (resources
        exhausted or every head blocked)."""
        progressed = True
        while progressed:
            progressed = False
            for key in self._squeue.keys():
                while True:
                    item = self._squeue.head(key)
                    if item is None:
                        break
                    outcome = self._try_dispatch_head(item)
                    if outcome == "dispatched":
                        self._squeue.pop_head(key)
                        self._squeue.rotate(key)
                        progressed = True
                        break  # one dispatch per class per turn
                    if outcome == "resolved":
                        # errored/evicted head: drop it and inspect the
                        # next item without losing this class's turn
                        self._squeue.pop_head(key)
                        progressed = True
                        continue
                    # "blocked": the class waits for local resources — but
                    # let a bounded window of it offload in PARALLEL
                    # (head-only spillback would drain a backlog onto an
                    # idle peer at one task per round-trip)
                    self._maybe_spill_class(key)
                    break  # next class's turn

    def _try_dispatch_head(self, item: Dict) -> str:
        """Attempt one head-of-class dispatch. Returns ``"dispatched"``
        (resources claimed, task launched), ``"resolved"`` (the item
        finished without running — error reply or deadline eviction; pop
        it) or ``"blocked"`` (the class waits for resources/spillback)."""
        payload = item["payload"]
        now = time.monotonic()
        if item.get("spilling"):
            return "blocked"  # a spillback attempt owns it
        if item["future"].done():
            return "resolved"  # owner gone / already answered elsewhere
        if item.get("expires") is not None and now > item["expires"]:
            self._evict_item(item, now)
            return "resolved"
        req = ResourceSet(payload["resources"])
        pg = payload.get("pg")
        if pg is not None:
            bundle = self._bundles.get((pg["pg_id"], pg["bundle_index"]))
            if bundle is None:
                self._failure_event(
                    F.PG_REMOVED,
                    "placement group bundle not on this node "
                    "(removed or rescheduled)",
                    task_id=payload.get("task_id"),
                    name=payload.get("fn_name"),
                    pg_id=pg.get("pg_id"))
                if not item["future"].done():
                    item["future"].set_result({
                        "error": "bundle_gone",
                        "message": "placement group bundle not on this "
                                   "node (removed or rescheduled)",
                        "cause": F.cause_dict(
                            F.PG_REMOVED,
                            "placement group bundle not on this "
                            "node (removed or rescheduled)",
                            node_id=self.node_id,
                            pg_id=pg.get("pg_id"))})
                return "resolved"
            if not bundle.pool.is_feasible(req):
                msg = (f"task requires {req.to_dict()} but "
                       f"its placement group bundle only has "
                       f"{bundle.pool.total.to_dict()}")
                self._failure_event(
                    F.SCHEDULING_TIMEOUT, msg,
                    task_id=payload.get("task_id"),
                    name=payload.get("fn_name"))
                if not item["future"].done():
                    item["future"].set_result({
                        "error": "infeasible", "message": msg,
                        "cause": F.cause_dict(
                            F.SCHEDULING_TIMEOUT, msg,
                            node_id=self.node_id)})
                return "resolved"
            pool = bundle.pool
        else:
            pool = self.node
        local_ok = pg is not None or strategy_allows_local(
            payload.get("strategy"), self.node_id, self.node.labels)
        if local_ok and pool.can_fit(req):
            assignment = pool.allocate(req)
            # placement receipt: local dispatches flood, but the GCS store
            # dedups same-shaped decisions into one counted row, so this
            # stays one cheap dict per dispatch on the wire at worst
            self._placement_event({
                "kind": "dispatch_local",
                "task_id": payload.get("task_id"),
                "name": payload.get("fn_name"),
                "node_id": self.node_id,
                "reason": "pg_bundle" if pg is not None else "local_fit",
                "candidates": [self._local_features(item.get("skey"),
                                                    payload)],
            })
            spawn_task(self._run_task(item, req, assignment, pool))
            return "dispatched"
        # Load-based spillback (reference: spillback replies in
        # ScheduleAndDispatchTasks) is handled class-wide by
        # _maybe_spill_class on the "blocked" return: a feasible task that
        # has waited past the delay looks for a node with capacity free
        # NOW. PG tasks are bundle-pinned — never spill; strategy-
        # ineligible tasks MUST route and are exempt from the hop cap.
        return "blocked"

    _SPILL_SCAN = 32   # items of a blocked class scanned for spillback
    _SPILL_CONC = 8    # concurrent spillback attempts per class

    def _maybe_spill_class(self, key: Tuple) -> None:
        """Mark up to ``_SPILL_CONC`` eligible items of a blocked class as
        spilling and launch their attempts. Eligibility mirrors the head
        path: never PG-pinned, hop cap honored (strategy-ineligible items
        are exempt), waited past the spillback delay, not expired."""
        cfg = get_config()
        now = time.monotonic()
        budget = self._SPILL_CONC
        launch = []
        for item in self._squeue.window(key, self._SPILL_SCAN):
            if item.get("spilling"):
                budget -= 1
                if budget <= 0:
                    break  # cap reached — still launch what we collected
                continue
            payload = item["payload"]
            if payload.get("pg") is not None or item["future"].done():
                continue
            if (item.get("expires") is not None
                    and now > item["expires"]):
                continue  # the sweep/head check sheds it
            local_ok = strategy_allows_local(
                payload.get("strategy"), self.node_id, self.node.labels)
            if ((not local_ok
                 or payload.get("spill_count", 0) < cfg.spillback_max_hops)
                    and now - item.get("t", 0) > cfg.spillback_delay_s):
                # stamp WHY the local node was rejected, while the local
                # view that rejected it is still in hand — the decision
                # record's reason must be truthful, not reconstructed
                if not local_ok:
                    item["spill_reason"] = "strategy_ineligible"
                elif not self.node.is_feasible(
                        ResourceSet(payload["resources"])):
                    item["spill_reason"] = "resource_infeasible"
                elif item.get("expires") is not None:
                    item["spill_reason"] = "deadline_pressure"
                else:
                    item["spill_reason"] = "queue_bound"
                launch.append(item)
                budget -= 1
                if budget <= 0:
                    break
        for item in launch:
            item["spilling"] = True
            spawn_task(self._try_spillback(item))

    def _evict_expired(self, now: Optional[float] = None) -> int:
        """Deadline sweep: shed every queued item whose budget expired
        (spillback-owned items are skipped — they are mid-move). Runs from
        the heartbeat loop; the dispatch head check catches the rest."""
        if not self._squeue.expiring:
            return 0  # nothing carries a deadline: skip the full scan
        now = time.monotonic() if now is None else now
        expired = [item for item in self._squeue.items()
                   if item.get("expires") is not None
                   and now > item["expires"] and not item.get("spilling")]
        for item in expired:
            if self._squeue.remove(item):
                self._evict_item(item, now)
        return len(expired)

    def _evict_item(self, item: Dict, now: float) -> None:
        """Deadline eviction: resolve the owner's submit with a
        ``scheduling_timeout`` cause (an ORGANIC failure-feed row — shed
        stale work is a real scheduling outcome, not an injected one) and
        count it. The caller removes the item from the queue."""
        payload = item["payload"]
        waited = now - item.get("t_enq", item["t"])
        msg = (f"deadline_s={payload.get('deadline_s')} budget expired "
               f"after {waited:.1f}s in the raylet queue (class "
               f"{item['label']!r}); stale work shed instead of executed "
               f"late")
        self._sched_stats["deadline_evictions"] += 1
        if self._telemetry:
            try:
                self._telemetry_metrics()["deadline_evictions"].inc(
                    1.0, {"node_id": self.node_id})
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        cause = F.cause_dict(F.SCHEDULING_TIMEOUT, msg,
                             node_id=self.node_id,
                             task_id=payload.get("task_id"))
        self._failure_event(F.SCHEDULING_TIMEOUT, msg,
                            task_id=payload.get("task_id"),
                            name=payload.get("fn_name"))
        self._task_event(payload["task_id"], payload.get("fn_name"),
                         "FAILED")
        fut = item["future"]
        if not fut.done():
            fut.set_result({"error": "deadline_exceeded", "message": msg,
                            "cause": cause})

    async def _run_task(self, item, req: ResourceSet, assignment,
                        pool: NodeResources) -> None:
        payload, fut = item["payload"], item["future"]
        task_id = payload["task_id"]
        chips = assignment.get(TPU, [])
        renv = payload.get("runtime_env")
        t_claim = time.monotonic()
        if self._telemetry:
            self._telemetry_metrics()["queue_wait"].observe(
                t_claim - item["t"], {"node_id": self.node_id})
        # per-class wait sample (feeds the heartbeat's wait_p99_s and the
        # doctor starvation finding); bounded ring per class label
        dq = self._class_waits.get(item.get("label") or "anonymous")
        if dq is None:
            dq = self._class_waits.setdefault(
                item.get("label") or "anonymous",
                collections.deque(maxlen=512))
        dq.append((t_claim, t_claim - item.get("t_enq", item["t"])))
        # Phase tracing (one predicate when untraced): this raylet owns
        # queue_wait / worker_acquire / transfer / sched_overhead; the
        # worker's reply contributes arg_fetch / execute / result_store.
        traced = payload.get("trace") is not None
        t_enq = item.get("t_enq", item["t"])
        phases: Optional[Dict[str, float]] = (
            {"queue_wait": t_claim - t_enq} if traced else None)
        source = None
        # worker reuse is keyed by (chip set, env hash): a process prepared
        # for one runtime env never executes another env's tasks (reference:
        # WorkerPool cache keyed by runtime-env hash)
        key = (tuple(chips), renv["hash"] if renv else None)
        self._inflight[task_id] = {"req": req, "released": ResourceSet(),
                                   "pool": pool}
        worker = None
        try:
            worker, source = await self._get_worker(key, chips, renv)
            # warm-pool accounting: a pool hit skipped an interpreter boot
            if source == "warm":
                self._sched_stats["warm_hits"] += 1
                if self._telemetry:
                    try:
                        self._telemetry_metrics()["warm_hits"].inc(
                            1.0, {"node_id": self.node_id, "kind": "task"})
                    except Exception:  # noqa: BLE001 — telemetry only
                        pass
            else:
                self._sched_stats["cold_spawns"] += 1
            f = C.maybe_fire("raylet.kill_worker",
                             target=payload.get("fn_name"))
            if f is not None:
                # kill the acquired worker just before the push: the push
                # fails, the normal worker_crash path runs, and the owner's
                # retry budget proves recovery. Counters live in this
                # long-lived raylet, so at/max_fires plans stay exact.
                self._chaos_stamp("raylet.kill_worker", f, task_id=task_id,
                                  name=payload.get("fn_name"),
                                  worker_id=worker.worker_id)
                try:
                    worker.proc.kill()
                except ProcessLookupError:
                    pass
            worker.busy = True
            worker.job_id = payload.get("job_id")
            worker.current_task = payload.get("fn_name")
            self._task_event(task_id, payload.get("fn_name"), "RUNNING")
            t_acq = time.monotonic()
            try:
                reply = await worker.client.call("push_task", payload)
            finally:
                self._release_worker(worker)
            failed = (reply.get("error")
                      or reply.get("stream_error") is not None)
            if traced:
                now = time.monotonic()
                phases["worker_acquire"] = t_acq - t_claim
                worker_phases = reply.pop("worker_phases", None) or {}
                worker_total = sum(worker_phases.values())
                phases.update(worker_phases)
                # push RPC + marshalling around the worker's own span;
                # also absorbs any raylet event-loop latency inside the
                # push window (the queue side of that latency is already
                # inside queue_wait)
                phases["transfer"] = max(0.0, (now - t_acq) - worker_total)
                reply["phases"] = phases
                reply["phases_total"] = now - t_enq
                reply["worker_source"] = source
            self._task_event(task_id, payload.get("fn_name"),
                             "FAILED" if failed else "FINISHED",
                             phases=phases, worker_source=source)
            if not fut.done():
                fut.set_result(reply)
        except Exception as e:  # worker crashed mid-task or failed to start
            self._task_event(task_id, payload.get("fn_name"), "FAILED")
            if worker is not None and worker.oom_killed:
                cause = F.cause_dict(
                    F.OOM_KILL,
                    f"memory monitor killed the worker running "
                    f"{payload.get('fn_name')!r} "
                    f"(node over memory_usage_threshold)",
                    node_id=self.node_id, task_id=task_id,
                    worker_id=worker.worker_id)
                err_kind = "oom_killed"
            else:
                cause = F.cause_dict(
                    F.WORKER_CRASH, repr(e), node_id=self.node_id,
                    task_id=task_id,
                    worker_id=worker.worker_id if worker else None)
                err_kind = "worker_crashed"
            self._failure_event(cause["category"], cause["message"],
                                task_id=task_id,
                                name=payload.get("fn_name"))
            if not fut.done():
                fut.set_result({"error": err_kind,
                                "message": cause["message"],
                                "cause": cause})
        finally:
            state = self._inflight.pop(task_id)
            pool.release(state["req"].subtract(state["released"]), assignment)
            self._dispatch_event.set()

    async def rpc_task_blocked(self, p):
        """A worker entered a blocking ``get`` inside a task: return its CPU
        to the pool so dependent tasks can run (the reference's
        blocked-worker CPU release — prevents parent-waits-on-child
        deadlock). The CPU is not re-acquired on unblock; it flows back when
        the task finishes."""
        state = self._inflight.get(p["task_id"])
        if state is None or not state["released"].is_empty():
            return {"ok": False}
        cpu_part = ResourceSet({CPU: state["req"].get(CPU)})
        if cpu_part.is_empty():
            return {"ok": False}
        state["released"] = cpu_part
        state["pool"].release(cpu_part)
        self._dispatch_event.set()
        return {"ok": True}

    # ---- placement group bundles -------------------------------------------
    async def rpc_prepare_bundle(self, p):
        """Phase 1 of the 2PC: reserve the bundle's resources (+chips)."""
        key = (p["pg_id"], p["bundle_index"])
        if key in self._bundles:
            return {"ok": True}  # idempotent re-prepare
        req = ResourceSet(p["resources"])
        if not self.node.can_fit(req):
            return {"ok": False, "retry": True}
        assignment = self.node.allocate(req)
        self._bundles[key] = _BundleState(req, assignment)
        return {"ok": True}

    async def rpc_commit_bundle(self, p):
        bundle = self._bundles.get((p["pg_id"], p["bundle_index"]))
        if bundle is None:
            return {"ok": False}
        bundle.committed = True
        return {"ok": True}

    async def rpc_release_bundle(self, p):
        bundle = self._bundles.pop((p["pg_id"], p["bundle_index"]), None)
        if bundle is not None:
            self.node.release(bundle.node_req, bundle.node_assignment)
            self._dispatch_event.set()
        return {"ok": True}

    def _actor_pool(self, spec) -> Optional[NodeResources]:
        pg = spec.get("pg")
        if pg is None:
            return self.node
        bundle = self._bundles.get((pg["pg_id"], pg["bundle_index"]))
        return bundle.pool if bundle is not None else None

    # ---- actors -------------------------------------------------------------
    async def rpc_create_actor(self, p):
        spec = p["spec"]
        req = ResourceSet(spec.get("resources", {}))
        pool = self._actor_pool(spec)
        if pool is None:
            return {"ok": False, "retry": True}  # bundle not here (yet)
        if not pool.can_fit(req):
            return {"ok": False, "retry": True}
        assignment = pool.allocate(req)
        chips = assignment.get(TPU, [])
        worker = None
        try:
            # Warm-pool adoption (reference: the worker pool handing a
            # prestarted worker to PopWorker): an actor that needs no
            # pinned chips and no runtime env takes over an idle pooled
            # worker instead of paying interpreter boot — the 0.4/s actor
            # spawn floor of SCALE_r05 was pure process startup.
            if (get_config().worker_adopt_for_actors and not chips
                    and not spec.get("runtime_env")):
                idle = self._idle.get(_WARM_KEY)
                while idle:
                    cand = idle.pop()
                    if cand.proc.poll() is None:
                        worker = cand
                        worker.idle_since = None
                        worker.key = (("actor", p["actor_id"]),)
                        self._sched_stats["warm_hits"] += 1
                        self._sched_stats["actor_adoptions"] += 1
                        if self._telemetry:
                            try:
                                self._telemetry_metrics()["warm_hits"].inc(
                                    1.0, {"node_id": self.node_id,
                                          "kind": "actor"})
                            except Exception:  # noqa: BLE001
                                pass
                        break
                    self._workers.pop(cand.worker_id, None)
                if worker is not None:
                    # placement receipt: adoption is a placement decision —
                    # the warm pool won over a cold spawn on this node
                    self._placement_event({
                        "kind": "warm_adopt",
                        "actor_id": p["actor_id"],
                        "name": spec.get("class_name"),
                        "node_id": self.node_id,
                        "reason": "warm_pool_hit",
                        "candidates": [self._local_features()],
                    })
            if worker is None:
                self._sched_stats["cold_spawns"] += 1
                worker = self._spawn_worker((("actor", p["actor_id"]),),
                                            chips, spec.get("runtime_env"))
            worker.is_actor_worker = True
            worker.job_id = spec.get("job_id")
            worker.actor_id = p["actor_id"]
            worker.assignment = assignment
            worker._spec_resources = spec.get("resources", {})
            worker._pool = pool
            cfg = get_config()
            await asyncio.wait_for(
                worker.ready,
                cfg.process_startup_timeout_s
                + (cfg.runtime_env_setup_timeout_s
                   if spec.get("runtime_env") else 0))
            reply = await worker.client.call("create_actor", p)
            if not reply.get("ok"):
                # Unmark before releasing so _reap_loop doesn't release the
                # same resources a second time (double-release would corrupt
                # chip accounting).
                worker.is_actor_worker = False
                pool.release(req, assignment)
                self._terminate_worker(worker)  # reap loop collects it
                # user code raised in __init__: a task-error-category death
                cause = F.cause_dict(
                    F.TASK_ERROR,
                    reply.get("error", "actor __init__ failed"),
                    node_id=self.node_id, actor_id=p["actor_id"])
                await self._gcs.call("actor_update", {
                    "actor_id": p["actor_id"], "state": "DEAD",
                    "node_id": self.node_id,
                    "reason": cause["message"], "cause": cause})
                return {"ok": False, "error": reply.get("error"),
                        "cause": cause}
            await self._gcs.call("actor_update", {
                "actor_id": p["actor_id"], "state": "ALIVE",
                "address": reply["address"], "node_id": self.node_id})
            return {"ok": True}
        except Exception as e:
            if worker is not None:
                worker.is_actor_worker = False
                self._terminate_worker(worker)  # reap loop collects it
            pool.release(req, assignment)
            category = (F.RUNTIME_ENV_SETUP
                        if spec.get("runtime_env")
                        and isinstance(e, asyncio.TimeoutError)
                        else F.WORKER_CRASH)
            cause = F.cause_dict(category, repr(e), node_id=self.node_id,
                                 actor_id=p["actor_id"])
            # no _failure_event here: the GCS records this same cause when
            # the create reply finalizes the actor (emitting both would
            # double rt_failures_total for one failure)
            return {"ok": False, "error": repr(e), "cause": cause}

    async def rpc_kill_actor(self, p):
        for entry in list(self._workers.values()):
            if entry.actor_id == p["actor_id"]:
                entry.is_actor_worker = False  # suppress DEAD re-report
                entry.actor_id = None  # a later duplicate kill is a no-op
                getattr(entry, "_pool", self.node).release(
                    ResourceSet(entry_spec_resources(entry)), entry.assignment)
                self._terminate_worker(entry)
        return {"ok": True}

    # ---- object plane -------------------------------------------------------
    _PIN_TTL_S = 120.0

    def _purge_stale_pins(self, now: float) -> int:
        """Drop leaked get-pins (crashed getters): live pins span only a
        fetch→read window, so a stale ``t`` means nobody is waiting. Runs
        on the reap-loop TIMER (not just when the pin path happens to get
        hot), so a leaked pin can't silently exempt its object from
        spilling for the life of the raylet. Purges are counted — leaked
        pins are a visible signal, not silent cleanup."""
        purged = 0
        for oid_hex, entry in list(self._pinned.items()):
            if now - entry["t"] > self._PIN_TTL_S:
                self._pinned.pop(oid_hex, None)
                purged += 1
        if purged:
            self._mem_stats["pin_purges"] += purged
            if self._telemetry:
                try:
                    self._telemetry_metrics()["pin_purges"].inc(
                        float(purged), {"node_id": self.node_id})
                except Exception:  # noqa: BLE001 — cleanup must proceed
                    pass
        return purged

    async def rpc_pin_objects(self, p):
        now = time.monotonic()
        if len(self._pinned) > 1024:
            self._purge_stale_pins(now)  # burst guard between timer ticks
        for oid_hex in p["oids"]:
            entry = self._pinned.setdefault(oid_hex, {"count": 0, "t": now})
            entry["count"] += 1
            entry["t"] = now
        return {"ok": True}

    async def rpc_unpin_objects(self, p):
        for oid_hex in p["oids"]:
            entry = self._pinned.get(oid_hex)
            if entry is None:
                continue
            entry["count"] -= 1
            if entry["count"] <= 0:
                self._pinned.pop(oid_hex, None)
        # released pins may allow the store to shrink back under threshold
        await self._maybe_spill()
        return {"ok": True}

    def _refresh_pin(self, oid_hex: str) -> None:
        """Restart the TTL clock for the fetch-ok→read window: a getter may
        have blocked in fetch far past the TTL (late producer), and the pin
        must be live precisely when the object lands in shm. Recreates the
        entry if the purge dropped it while the getter was blocked."""
        entry = self._pinned.get(oid_hex)
        if entry is None:
            entry = self._pinned[oid_hex] = {"count": 1, "t": 0.0}
        entry["t"] = time.monotonic()

    def _is_pinned(self, oid_hex: str, now: float) -> bool:
        """Read-only (called from the spill executor thread; mutation happens
        only on the event loop). A stale ``t`` — crashed getter — is treated
        as unpinned but left for the event loop to purge."""
        entry = self._pinned.get(oid_hex)
        return entry is not None and now - entry["t"] <= self._PIN_TTL_S

    def _spill_path(self, oid_hex: str) -> str:
        return os.path.join(self._spill_dir, oid_hex)

    def _touch(self, oid_hex: str, size: Optional[int] = None,
               spilled: Optional[bool] = None) -> None:
        meta = self._object_meta.setdefault(
            oid_hex, {"size": 0, "t": 0.0, "spilled": False})
        before = 0 if meta["spilled"] else meta["size"]
        meta["t"] = time.monotonic()
        if size is not None:
            meta["size"] = size
        if spilled is not None:
            meta["spilled"] = spilled
        self._in_mem_bytes += (0 if meta["spilled"] else meta["size"]) - before

    async def _maybe_spill(self) -> None:
        """Capacity enforcement: when sealed bytes exceed the spill
        threshold, move least-recently-used objects out of shm onto disk
        (reference: ``LocalObjectManager::SpillObjects`` dispatched by the
        plasma LRU ``EvictionPolicy``). File IO runs on the spill executor so
        the raylet keeps dispatching. Locations in the GCS stay valid — this
        node still serves the object, just from disk."""
        # Cheap loop-side precheck: don't bounce through the executor (and
        # its lock) when the store is under threshold — unpin calls this on
        # every fetch. The spill thread re-checks exactly under the lock.
        cfg = get_config()
        threshold = self._store_capacity * cfg.object_spill_threshold
        if 0 <= self._in_mem_bytes <= threshold:
            return  # negative = drift; fall through so the pass resyncs
        spilled = await asyncio.get_running_loop().run_in_executor(
            self._spill_exec, self._spill_blocking)
        # telemetry off the IO thread: histograms + instant events per
        # spilled object (the byte-side twin of the queue-wait histogram)
        for oid_hex, size, secs in spilled or ():
            self._mem_stats["spills"] += 1
            self._mem_stats["spill_bytes"] += size
            self._mem_stats["spill_seconds"] += secs
            if self._telemetry:
                self._telemetry_metrics()["spill_hist"].observe(
                    secs, {"node_id": self.node_id})
            self._mem_event("spill", oid=oid_hex, size=size, seconds=secs)

    def _spill_blocking(self) -> List[Tuple[str, int, float]]:
        """Returns [(oid_hex, size, io_seconds)] for each object spilled."""
        cfg = get_config()
        threshold = self._store_capacity * cfg.object_spill_threshold
        out: List[Tuple[str, int, float]] = []
        with self._spill_lock:
            now = time.monotonic()
            in_mem = [(oid, m) for oid, m in self._object_meta.items()
                      if not m["spilled"]]
            used = sum(m["size"] for _, m in in_mem)
            if used <= threshold:
                return out
            in_mem.sort(key=lambda kv: kv[1]["t"])  # LRU first
            os.makedirs(self._spill_dir, exist_ok=True)
            for oid_hex, meta in in_mem:
                if used <= threshold:
                    break
                if self._is_pinned(oid_hex, now):
                    continue  # a getter holds this between fetch and read
                view = self.store.read(ObjectID.from_hex(oid_hex))
                if view is None:
                    meta["spilled"] = True  # vanished (e.g. freed mid-scan)
                    used -= meta["size"]
                    continue
                t0 = time.monotonic()
                fault = C.maybe_fire("spill.slow", target=oid_hex)
                if fault is not None:
                    # slow-disk injection (spill executor thread, so the
                    # stall hits the IO histogram, not the event loop)
                    self._chaos_stamp("spill.slow", fault, oid=oid_hex)
                    # rt: lint-allow(lock-discipline) chaos injection: the
                    # stall deliberately holds the spill lock like a real
                    # slow disk would (spill executor thread, not the loop)
                    time.sleep(float(fault.get("delay_s", 0.2)))
                tmp = self._spill_path(oid_hex) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(view)
                meta["crc"] = _native.crc32c(view)
                os.rename(tmp, self._spill_path(oid_hex))
                self.store.delete(ObjectID.from_hex(oid_hex))
                meta["spilled"] = True
                used -= meta["size"]
                out.append((oid_hex, meta["size"], time.monotonic() - t0))
            # Exact resync of the O(1)-precheck counter: per-op increments
            # race across the loop/executor threads (non-atomic RMW, frees
            # during the scan); recomputing under the lock bounds any drift
            # to one spill pass.
            self._in_mem_bytes = sum(
                m["size"] for m in self._object_meta.values()
                if not m["spilled"])
        return out

    async def _restore_from_spill(self, oid_hex: str) -> bool:
        """Disk -> shm (reference: ``SpilledObjectReader`` restore path)."""
        t0 = time.monotonic()
        restored = await asyncio.get_running_loop().run_in_executor(
            self._spill_exec, self._restore_blocking, oid_hex)
        if restored:
            secs = time.monotonic() - t0
            size = self._object_meta.get(oid_hex, {}).get("size", 0)
            self._mem_stats["restores"] += 1
            self._mem_stats["restore_bytes"] += size
            self._mem_stats["restore_seconds"] += secs
            if self._telemetry:
                self._telemetry_metrics()["restore_hist"].observe(
                    secs, {"node_id": self.node_id})
            self._mem_event("restore", oid=oid_hex, size=size, seconds=secs)
            await self._maybe_spill()  # restoring may push something else out
        return restored

    def _restore_blocking(self, oid_hex: str) -> bool:
        with self._spill_lock:
            path = self._spill_path(oid_hex)
            if not os.path.exists(path):
                return False
            with open(path, "rb") as f:
                payload = f.read()
            expected = self._object_meta.get(oid_hex, {}).get("crc")
            if expected is not None:
                if _native.crc32c(payload) != expected:
                    # corrupt spill file: drop it; the owner reconstructs
                    # from lineage (better loud loss than silent corruption)
                    try:
                        os.unlink(path)
                    except FileNotFoundError:
                        pass
                    return False
            oid = ObjectID.from_hex(oid_hex)
            if not self.store.contains(oid):
                self.store.write_whole(oid, payload)
            self._touch(oid_hex, size=len(payload), spilled=False)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return True

    async def rpc_seal_object(self, p):
        oid_hex = p["oid"]
        self._local_objects.add(oid_hex)
        self._touch(oid_hex, size=p.get("size", 0), spilled=False)
        await self._maybe_spill()
        # degraded-aware: a sealed object must not fail its task because
        # the GCS is briefly unreachable — the location defers + resyncs
        await self._gcs_publish("add_object_location", {
            "oid": oid_hex, "node_id": self.node_id, "size": p.get("size", 0)})
        f = C.maybe_fire("object.lose", target=oid_hex)
        if f is not None:
            # silent-loss injection: the location is registered but the
            # payload vanishes — every later get must run the owner's
            # lineage reconstruction (the recovery path under test)
            self._chaos_stamp("object.lose", f, oid=oid_hex)
            self._drop_object_copies(oid_hex)
        return {"ok": True}

    def _drop_object_copies(self, oid_hex: str) -> None:
        """Delete every local copy of an object (shm + spill + meta) —
        the chaos object-loss effect."""
        try:
            self.store.delete(ObjectID.from_hex(oid_hex))
        except Exception:  # noqa: BLE001
            pass
        meta = self._object_meta.pop(oid_hex, None)
        if meta is not None and not meta.get("spilled"):
            self._in_mem_bytes -= meta["size"]
        try:
            os.unlink(self._spill_path(oid_hex))
        except OSError:
            pass

    async def rpc_get_object_payload(self, p):
        oid_hex = p["oid"]
        view = self.store.read(ObjectID.from_hex(oid_hex))
        if view is not None:
            self._touch(oid_hex)
            return {"payload": bytes(view)}
        path = self._spill_path(oid_hex)

        def read_spill():
            # spill-file IO off the event loop: a slow disk must not
            # stall heartbeats/dispatch (the spill pool already owns
            # this discipline for writes)
            try:
                with open(path, "rb") as f:
                    return f.read()
            except OSError:
                return None

        payload = await asyncio.get_running_loop().run_in_executor(
            self._spill_exec, read_spill)
        if payload is not None:
            return {"payload": payload}
        return {"error": "not found"}

    async def rpc_put_object_chunk(self, p):
        """Client-mode upload: a process WITHOUT shared shm (Ray-Client
        analog) streams an object into this node's store in bounded chunks;
        the final chunk seals + registers the location."""
        oid_hex = p["oid"]
        oid = ObjectID.from_hex(oid_hex)
        off, total, data = p["offset"], p["total"], p["data"]
        try:
            if off == 0:
                if self.store.contains(oid):
                    return {"ok": True, "dup": True}
                self._client_uploads[oid_hex] = (
                    self.store.create(oid, total), time.monotonic())
            entry = self._client_uploads.get(oid_hex)
            if entry is None:
                return {"error": "upload not started"}
            buf = entry[0]
            # TTL tracks last ACTIVITY, not start: a slow-but-live upload
            # must never be reaped mid-stream
            self._client_uploads[oid_hex] = (buf, time.monotonic())
            buf[off:off + len(data)] = data
            if p.get("seal"):
                self._client_uploads.pop(oid_hex, None)
                self.store.seal(oid)
                self._local_objects.add(oid_hex)
                self._touch(oid_hex, size=total, spilled=False)
                await self._maybe_spill()
                await self._gcs_publish("add_object_location", {
                    "oid": oid_hex, "node_id": self.node_id, "size": total})
            return {"ok": True}
        except Exception as e:  # noqa: BLE001 — drop partial upload
            self._client_uploads.pop(oid_hex, None)
            try:
                self.store.delete(oid)
            except Exception:  # noqa: BLE001
                pass
            return {"error": repr(e)}

    async def rpc_get_object_chunk(self, p):
        """Serve one bounded slice of an object (reference: chunked reads,
        ``object_manager/chunk_object_reader.h``); shm and spill-file copies
        both serve — the puller never needs the whole payload in one frame."""
        oid_hex, off, size = p["oid"], p["offset"], p["size"]
        kind = _native.checksum_kind()
        view = self.store.read(ObjectID.from_hex(oid_hex))
        if view is not None:
            self._touch(oid_hex)
            data = bytes(view[off:off + size])
            return {"total": len(view), "data": data,
                    "crc": _native.crc32c(data), "crc_kind": kind}
        path = self._spill_path(oid_hex)

        def read_slice():
            # spill-file IO off the event loop (see rpc_get_object_payload)
            total = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(off)
                return total, f.read(size)

        try:
            total, data = await asyncio.get_running_loop().run_in_executor(
                self._spill_exec, read_slice)
            return {"total": total, "data": data,
                    "crc": _native.crc32c(data), "crc_kind": kind}
        except FileNotFoundError:
            return {"error": "not found"}

    async def _pull_chunked(self, client, oid, oid_hex: str) -> Optional[int]:
        """Pull a remote object into local shm in bounded chunks, writing
        straight into the store's mmap (peak memory = one chunk). Returns
        the object size, or None if the source doesn't have it."""
        def _checked(reply) -> Optional[bytes]:
            data = reply.get("data")
            if data is None:
                return None
            crc = reply.get("crc")
            if crc is not None:
                # verify with the ALGORITHM THE SENDER USED — a mixed
                # native/fallback cluster must not fail every transfer
                ours = _native.checksum(data, reply.get("crc_kind", "crc32c"))
                if ours is not None and ours != crc:
                    raise ConnectionError(
                        f"chunk checksum mismatch for {oid_hex} "
                        f"(corruption in transit)")
            return data

        chunk = get_config().object_transfer_chunk_bytes
        first = await client.call("get_object_chunk",
                                  {"oid": oid_hex, "offset": 0, "size": chunk})
        if "data" not in first:
            return None
        total = first["total"]
        first_data = _checked(first)
        if total <= len(first_data):
            self.store.write_whole(oid, first_data)
            return total
        buf = self.store.create(oid, total)
        try:
            n = len(first_data)
            buf[:n] = first_data
            off = n
            while off < total:
                r = await client.call(
                    "get_object_chunk",
                    {"oid": oid_hex, "offset": off, "size": chunk})
                data = _checked(r)
                if not data:  # source freed/evicted mid-transfer
                    raise ConnectionError("chunk source went away")
                buf[off:off + len(data)] = data
                off += len(data)
            self.store.seal(oid)
            return total
        except Exception:
            self.store.delete(oid)  # drop the partial .building file
            raise

    async def rpc_fetch_object(self, p):
        """Pull an object to this node's store (reference: PullManager →
        remote ObjectManager chunked push). Resolution: local shm → local
        spill restore → remote node (which itself serves shm or spill)."""
        oid_hex = p["oid"]
        oid = ObjectID.from_hex(oid_hex)
        if self.store.contains(oid):
            self._touch(oid_hex)
            self._refresh_pin(oid_hex)
            return {"ok": True}
        if await self._restore_from_spill(oid_hex):
            self._refresh_pin(oid_hex)
            return {"ok": True}
        inflight = self._pulls.get(oid_hex)
        if inflight is not None:  # join the pull already transferring this
            reply = await asyncio.shield(inflight)
            if reply.get("ok"):
                self._refresh_pin(oid_hex)
            return reply
        fut = asyncio.get_running_loop().create_future()
        self._pulls[oid_hex] = fut
        try:
            reply = await self._do_fetch(oid, oid_hex,
                                         p.get("timeout", 30.0))
        except Exception as e:  # noqa: BLE001 — joiners need a result too
            reply = {"error": "unavailable", "oid": oid_hex,
                     "message": repr(e)}
        finally:
            self._pulls.pop(oid_hex, None)
            if not fut.done():
                fut.set_result(reply)
        return reply

    async def _do_fetch(self, oid, oid_hex: str, timeout: float) -> Dict:
        reply = await self._gcs.call("get_object_locations", {
            "oid": oid_hex, "wait": True, "timeout": timeout})
        for loc in reply["locations"]:
            if loc["node_id"] == self.node_id:
                continue
            try:
                client = await self._pool.get(loc["address"])
                total = await self._pull_chunked(client, oid, oid_hex)
                if total is not None:
                    self._refresh_pin(oid_hex)
                    await self.rpc_seal_object({"oid": oid_hex,
                                                "size": total})
                    return {"ok": True}
            except Exception:
                continue
        if self.store.contains(oid) or await self._restore_from_spill(oid_hex):
            self._refresh_pin(oid_hex)
            return {"ok": True}
        return {"error": "unavailable", "oid": oid_hex}

    async def rpc_free_objects(self, p):
        for oid_hex in p["oids"]:
            self.store.delete(ObjectID.from_hex(oid_hex))
            self._local_objects.discard(oid_hex)
            meta = self._object_meta.pop(oid_hex, None)
            if meta is not None and not meta["spilled"]:
                self._in_mem_bytes -= meta["size"]
            self._pinned.pop(oid_hex, None)
            try:
                os.unlink(self._spill_path(oid_hex))
            except FileNotFoundError:
                pass
            await self._gcs_publish("remove_object_location", {
                "oid": oid_hex, "node_id": self.node_id})
        return {"ok": True}

    async def rpc_node_stats(self, p):
        return {
            "node_id": self.node_id,
            "workers": len(self._workers),
            "idle": sum(len(v) for v in self._idle.values()),
            "queued": len(self._squeue),
            "sched": self._sched_summary(),
            "object_store_bytes": self.store.used_bytes(),
            "available": self.node.available.to_dict(),
        }

    async def rpc_memory_report(self, p):
        """Node memory introspection for memory_summary() / `rt memory`:
        store usage by state, cumulative spill/restore/OOM counters, the
        per-object table (largest first, bounded by ``limit``) and live
        worker RSS (reference: the NodeManager stats behind
        ``ray memory`` / ``memory_summary``)."""
        now_mono = time.monotonic()
        states = self._store_state_bytes()
        limit = p.get("limit") or 200
        objects = []
        # snapshot first: the spill/restore executor thread inserts keys
        # concurrently, and a plain .items() walk could see a resize
        meta_items = list(self._object_meta.items())
        for oid_hex, meta in meta_items:
            pinned = self._is_pinned(oid_hex, now_mono)
            objects.append({
                "oid": oid_hex, "size": meta["size"],
                "state": ("spilled" if meta.get("spilled")
                          else "pinned" if pinned else "in_memory"),
                "age_s": max(0.0, now_mono - meta["t"]),
                "pinned": pinned})
        objects.sort(key=lambda d: -d["size"])
        by_pid = {e.proc.pid: e for e in self._workers.values()
                  if e.proc.poll() is None}
        workers = [{
            "worker_id": by_pid[pid].worker_id, "pid": pid, "rss": rss,
            "busy": by_pid[pid].busy,
            "actor_id": by_pid[pid].actor_id,
            "task": by_pid[pid].current_task}
            for pid, rss in _native.process_memory(list(by_pid))
            if pid in by_pid]
        mem = _native.memory_info()
        return {
            "node_id": self.node_id,
            "address": self.server.address,
            "node_memory": {"total": mem.get("total", -1),
                            "used": mem.get("used", -1)},
            "store": {
                "used_bytes": self.store.used_bytes(),
                "capacity_bytes": self._store_capacity,
                "in_mem_bytes": states["in_memory"],
                "spilled_bytes": states["spilled"],
                "pinned_bytes": states["pinned"],
                "spilled_count": sum(
                    1 for _, m in meta_items if m.get("spilled")),
                "pinned_count": len(self._pinned),
                "num_objects": len(meta_items),
                **{k: v for k, v in self._mem_stats.items()},
            },
            "objects": objects[:limit],
            "workers": workers,
        }

    async def rpc_dump_stacks(self, p):
        """Node-wide live stack capture (the py-spy-equivalent endpoint,
        reference ``dashboard/modules/reporter/profile_manager.py:11``):
        this raylet's threads + every live worker's, via each worker's
        ``dump_stacks`` RPC. A worker that can't respond in time (GIL held
        by native code) is reported unreachable rather than hanging the
        whole capture."""
        out = [{"pid": os.getpid(), "role": "raylet",
                "stacks": format_current_stacks()}]

        async def one(entry):
            info = {"pid": entry.proc.pid, "role": "actor"
                    if entry.is_actor_worker else "worker",
                    "worker_id": entry.worker_id, "busy": entry.busy}
            try:
                if entry.client is None:
                    raise RuntimeError("not yet registered")
                reply = await asyncio.wait_for(
                    entry.client.call("dump_stacks", {}),
                    timeout=p.get("timeout", 3.0))
                info["stacks"] = reply["stacks"]
            except Exception as e:  # noqa: BLE001 — report, don't fail
                info["unreachable"] = f"{type(e).__name__}: {e}"
            return info

        live = [e for e in self._workers.values()
                if e.proc.poll() is None]
        out.extend(await asyncio.gather(*(one(e) for e in live)))
        return {"node_id": self.node_id, "processes": out}


def entry_spec_resources(entry) -> Dict[str, float]:
    return getattr(entry, "_spec_resources", {})
