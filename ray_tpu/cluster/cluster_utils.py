"""Multi-node-in-one-process test cluster.

Reference analog: ``python/ray/cluster_utils.py`` (``Cluster``, ``add_node
:168``, ``remove_node :241``) — real control planes with FAKE resource
counts, so scheduler/placement tests run anywhere: a "TPU node" here is a
raylet that claims ``num_tpus=4``; tasks scheduled to it get chip indices
assigned without any hardware (the chips only matter when user code actually
touches jax).
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu._private.ids import JobID
from ray_tpu.cluster.driver_backend import ClusterHandle
from ray_tpu.cluster.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 gcs_persist_path: Optional[str] = None):
        self._handle = ClusterHandle()
        self._handle.start_gcs(persist_path=gcs_persist_path)
        self.head_node: Optional[Raylet] = None
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))

    @property
    def gcs_address(self) -> str:
        return self._handle.gcs_address

    def add_node(self, *, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Raylet:
        return self._handle.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                                     resources=resources, labels=labels)

    def remove_node(self, node: Raylet) -> None:
        self._handle.remove_node(node)

    def kill_gcs(self) -> None:
        """Chaos: crash the head (reference NodeKiller-style fault
        injection, ``_private/test_utils.py:1401``)."""
        self._handle.kill_gcs()

    def restart_gcs(self) -> str:
        return self._handle.restart_gcs()

    def connect_driver(self, namespace: Optional[str] = None):
        """Attach the global worker to this cluster as a driver."""
        import ray_tpu
        from ray_tpu.cluster.worker_core import ClusterBackend
        from ray_tpu.core.worker import global_worker

        job_id = JobID.from_random()
        raylet = self.head_node or self._handle.raylets[0]
        backend = ClusterBackend(
            gcs_address=self.gcs_address,
            raylet_address=raylet.server.address,
            node_id=raylet.node_id,
            session_name=self._handle.session_name,
            job_id=job_id, role="driver")
        backend.connect()
        global_worker().connect(backend, job_id, "driver")
        return backend

    def shutdown(self) -> None:
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        else:
            self._handle.shutdown()
            return
        # shutdown() above tears down the backend; the handle still owns the
        # control-plane components if no driver was attached.
        try:
            self._handle.shutdown()
        except Exception:
            pass
