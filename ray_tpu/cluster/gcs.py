"""GCS: the cluster control plane (head-node metadata + actor lifecycle).

Reference analog: ``src/ray/gcs/gcs_server/`` — node membership + health
(``GcsNodeManager``, ``GcsHealthCheckManager``), actor lifecycle + restart
(``GcsActorManager``/``GcsActorScheduler``), internal KV (``GcsKvManager``,
also the function table), the object directory, and named actors. State is
in-memory (a Redis-backed store client is a later round's HA concern).

Long-poll futures replace the reference's pubsub channels for the two hot
subscriptions (actor-alive, object-location): O(#waiters) wakeups, no
polling.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import get_config
from ray_tpu.core import failure as F
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.cluster.rpc import ConnectionPool, spawn_task
from ray_tpu.scheduler.policy import pick_node
from ray_tpu.util import chaos as C

ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class _NodeEntry:
    def __init__(self, node_id: str, address: str, resources: Dict[str, float],
                 labels: Dict[str, str]):
        self.node_id = node_id
        self.address = address
        self.view = NodeResources(resources, labels)
        self.alive = True
        self.last_heartbeat = time.monotonic()


class _ActorEntry:
    def __init__(self, actor_id: str, spec: Dict[str, Any]):
        self.actor_id = actor_id
        self.spec = spec                      # picklable creation spec
        self.state = ACTOR_PENDING
        self.address: Optional[str] = None
        self.node_id: Optional[str] = None
        self.num_restarts = 0
        self.death_reason = ""           # str(death_cause): legacy renderers
        self.death_cause: Optional[Dict[str, Any]] = None  # failure.py wire
        self.waiters: List[asyncio.Future] = []

    def __getstate__(self):  # snapshot persistence: waiters are loop-affine
        state = dict(self.__dict__)
        state["waiters"] = []
        return state

    def info(self) -> Dict[str, Any]:
        return {
            "actor_id": self.actor_id, "state": self.state,
            "address": self.address, "node_id": self.node_id,
            "name": self.spec.get("name"), "namespace": self.spec.get("namespace"),
            "class_name": self.spec.get("class_name"),
            "num_restarts": self.num_restarts,
            "death_reason": self.death_reason,
            "death_cause": getattr(self, "death_cause", None),
            "max_task_retries": self.spec.get("max_task_retries", 0),
        }


PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"


class _PgEntry:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = PG_PENDING
        self.bundle_nodes: List[Optional[str]] = [None] * len(bundles)
        self.waiters: List[asyncio.Future] = []
        self._rr = 0  # round-robin pointer for bundle_index=-1 routing

    def __getstate__(self):  # snapshot persistence: waiters are loop-affine
        state = dict(self.__dict__)
        state["waiters"] = []
        return state

    def info(self) -> Dict[str, Any]:
        return {"pg_id": self.pg_id, "state": self.state, "name": self.name,
                "strategy": self.strategy, "bundles": self.bundles,
                "bundle_nodes": list(self.bundle_nodes)}


def _strategy_kind(strategy: Any) -> str:
    """Reason token for a placement receipt: the scheduling strategy's kind
    (a strategy arrives over RPC as an object or a plain dict)."""
    if strategy is None:
        return "default"
    if isinstance(strategy, dict):
        return str(strategy.get("kind", "default")).lower()
    return str(getattr(strategy, "kind", strategy)).lower()


def imbalance_cov(loads: List[float]) -> float:
    """Population coefficient of variation (std/mean) of per-node load.

    0.0 means perfectly balanced; degenerate inputs (fewer than two nodes,
    or an idle cluster with zero mean) are defined as balanced rather than
    undefined — a one-node cluster can't be imbalanced.
    """
    vals = [float(v) for v in loads]
    if len(vals) < 2:
        return 0.0
    mean = sum(vals) / len(vals)
    if mean <= 0:
        return 0.0
    var = sum((v - mean) ** 2 for v in vals) / len(vals)
    return (var ** 0.5) / mean


class GcsServer:
    def __init__(self, persist_path: Optional[str] = None):
        self.nodes: Dict[str, _NodeEntry] = {}
        self.kv: Dict[str, bytes] = {}
        self.actors: Dict[str, _ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}
        self.placement_groups: Dict[str, _PgEntry] = {}
        self.object_locations: Dict[str, Set[str]] = {}
        self.object_sizes: Dict[str, int] = {}
        self._location_waiters: Dict[str, List[asyncio.Future]] = {}
        self._pool = ConnectionPool(peer_id="gcs")
        self._monitor_task: Optional[asyncio.Task] = None
        self._job_counter = 0
        # chaos-plan revision (snapshotted): a restarted head must NOT come
        # back at rev 0 while the KV still holds the plan — raylets would
        # see a rev change, re-arm, and reset spent kill-once fire budgets
        self._chaos_rev = 0
        # Snapshot persistence (reference: the Redis store client behind the
        # GCS tables, ``store_client/redis_store_client.cc`` — here a pickle
        # snapshot so a restarted head recovers actors/PGs/locations, plus a
        # crc-framed append-only WAL (native LogKV) for the user KV table:
        # every kv_put is appended+flushed before the ack, so it survives a
        # GCS *process* crash; fsync happens at migration/shutdown (or per
        # record with RT_WAL_FSYNC=1), so host-crash/power-loss durability
        # is opt-in. Multi-MB runtime-env packages also stop being
        # re-pickled into each snapshot.
        self._persist_path = persist_path
        self._persist_seq = self._persisted_seq = 0
        self._kv_log = None
        self._kv_log_exec = None
        if persist_path:
            self._restore_snapshot()
            try:
                from concurrent.futures import ThreadPoolExecutor

                from ray_tpu import _native

                import os as _os

                wal_path = persist_path + ".kv"
                # A non-empty WAL is AUTHORITATIVE for kv, including
                # deletions: snapshot-held keys must not be merged over it
                # (a tombstoned key is absent from keys(), so a merge would
                # resurrect durably-deleted data).
                fresh_wal = (not _os.path.exists(wal_path)
                             or _os.path.getsize(wal_path) == 0)
                self._kv_log = _native.LogKV(wal_path)
                if fresh_wal:
                    # one-time migration of pre-WAL snapshot keys, then an
                    # immediate kv={} snapshot so the old copy can't shadow
                    # later WAL deletes
                    for k, v in self.kv.items():
                        self._kv_log.put(k, self._encode_kv(v))
                    self._kv_log.sync()
                else:
                    wal_kv = {k: self._decode_kv(self._kv_log.get(k))
                              for k in self._kv_log.keys()}
                    if self.kv:
                        # A healthy lifecycle persists kv={} snapshots while
                        # the WAL is active, so a NON-empty snapshot kv next
                        # to a non-empty WAL means a previous run couldn't
                        # open the WAL and acked puts into the snapshot
                        # (degraded mode). Overlay those puts back into the
                        # WAL instead of silently discarding them; deletes
                        # acked during the degraded run are unrecoverable
                        # (no tombstone was written) and may resurrect.
                        import logging

                        changed = {k: v for k, v in self.kv.items()
                                   if wal_kv.get(k) != v}
                        for k, v in changed.items():
                            self._kv_log.put(k, self._encode_kv(v))
                        if changed:
                            self._kv_log.sync()
                            logging.getLogger("ray_tpu.gcs").warning(
                                "KV WAL re-opened after a degraded run: "
                                "merged %d snapshot-acked put(s) back into "
                                "the WAL. Deletes acked while the WAL was "
                                "unavailable were not tombstoned and may "
                                "have resurrected.", len(changed))
                        wal_kv.update(changed)
                    self.kv = wal_kv
                # single thread => append order == table order per key
                self._kv_log_exec = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rt-gcs-kvlog")
                self.mark_dirty()
                self._persist_snapshot()
            except Exception as e:  # noqa: BLE001 — WAL an upgrade, not a dep
                import logging
                import os as _os

                self._kv_log = None
                wal_path = persist_path + ".kv"
                if _os.path.exists(wal_path) and _os.path.getsize(wal_path):
                    # A WAL exists but could not be opened/replayed. Earlier
                    # runs snapshot kv={} once the WAL is authoritative, so
                    # falling back silently would present an EMPTY durable KV
                    # (runtime-env packages, job/function tables) while the
                    # real data still sits in the unreadable file. Run
                    # degraded but say so loudly; the file is left intact for
                    # a later restart to recover.
                    logging.getLogger("ray_tpu.gcs").error(
                        "KV WAL %s exists but failed to open (%s: %s) — "
                        "durable KV from previous runs is NOT loaded this "
                        "run, and new puts are snapshot-only until a restart "
                        "re-opens the WAL.", wal_path, type(e).__name__, e)
                else:
                    logging.getLogger("ray_tpu.gcs").warning(
                        "KV WAL unavailable (%s: %s); falling back to "
                        "snapshot-only KV persistence.", type(e).__name__, e)
            # A restarted head with a persisted chaos plan must RE-ARM its
            # own process (GCS-local sites + its ConnectionPool clients) —
            # otherwise rt chaos status would report armed cluster-wide
            # while the head itself runs dead. Raylets stay armed on their
            # own; the unchanged rev means no re-sync churn.
            raw = self.kv.get(self._CHAOS_KEY)
            if raw:
                try:
                    C.arm(raw.decode() if isinstance(raw, bytes) else raw,
                          rev=max(1, self._chaos_rev))
                    self._chaos_rev = max(1, self._chaos_rev)
                except (ValueError, TypeError):
                    pass

    @staticmethod
    def _encode_kv(value) -> bytes:
        """Type-tagged WAL value: callers pass str OR bytes and must get the
        same type back after a restart."""
        if isinstance(value, str):
            return b"s" + value.encode()
        return b"b" + bytes(value)

    @staticmethod
    def _decode_kv(blob: bytes):
        if blob[:1] == b"s":
            return blob[1:].decode()
        return bytes(blob[1:])

    def mark_dirty(self) -> None:
        self._persist_seq += 1

    # failure_events/_failure_seq are lazily created by _record_failure —
    # snapshot/restore tolerate their absence
    _SNAPSHOT_TABLES = ("kv", "actors", "named_actors", "placement_groups",
                        "object_locations", "object_sizes", "_job_counter",
                        "_chaos_rev", "failure_events", "_failure_seq")

    def _persist_snapshot(self) -> None:
        if not self._persist_path or self._persist_seq == self._persisted_seq:
            return
        seq = self._persist_seq
        self._write_snapshot(self._snapshot_tables())
        self._persisted_seq = seq

    def _write_snapshot(self, state: Dict) -> None:
        import os

        # unique tmp per writer: a stop()-time sync write racing an
        # in-flight executor write must never interleave on one file
        tmp = f"{self._persist_path}.tmp.{os.getpid()}.{id(state)}"
        os.makedirs(os.path.dirname(self._persist_path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._persist_path)

    def _snapshot_tables(self) -> Dict:
        """Loop-side copies: shallow for scalar tables, per-value copies for
        mutable containers (location sets mutate mid-pickle otherwise)."""
        state: Dict[str, Any] = {}
        for name in self._SNAPSHOT_TABLES:
            table = getattr(self, name, None)
            if table is None:
                continue  # lazily-created table never materialized
            if name == "kv" and self._kv_log is not None:
                state[name] = {}  # the WAL is the KV's source of truth
            elif name == "object_locations":
                state[name] = {k: set(v) for k, v in table.items()}
            elif name == "failure_events":
                # per-row copies: the dedup path mutates rows in place
                # (count/last_t) and a row changing size mid-pickle on the
                # executor thread would corrupt the snapshot
                state[name] = [dict(e) for e in table]
            elif isinstance(table, dict):
                state[name] = dict(table)
            else:
                state[name] = table
        return state

    def _restore_snapshot(self) -> None:
        import os

        if not os.path.exists(self._persist_path):
            return
        try:
            with open(self._persist_path, "rb") as f:
                state = pickle.load(f)
        except Exception:
            return  # corrupt snapshot: start fresh rather than crash
        for name in self._SNAPSHOT_TABLES:
            if name in state:
                setattr(self, name, state[name])
        if isinstance(self.__dict__.get("failure_events"), list):
            # the feed survives a head restart (a chaos gcs.kill must stay
            # attributable after its own kill): rebuild the bounded deque
            # and reset the dedup index (cross-restart dedup not needed)
            from collections import deque

            self.failure_events = deque(self.failure_events,
                                        maxlen=self._FAILURE_EVENTS_CAP)
            self._failure_last = {}
            self._failure_seq = int(self.__dict__.get("_failure_seq", 0)
                                    or len(self.failure_events))
        # Restored ALIVE actors may still be running (their workers outlive
        # a GCS restart); callers re-resolve addresses on first use. Nodes
        # are NOT restored — raylets re-register with their next heartbeat.

    def start_monitor(self) -> None:
        self._monitor_task = asyncio.ensure_future(self._monitor_loop())

    async def stop(self) -> None:
        from ray_tpu.cluster.rpc import cancel_and_wait

        await cancel_and_wait(self._monitor_task)
        self._monitor_task = None
        try:
            self._persist_snapshot()
        except Exception:
            pass
        if self._kv_log is not None:
            try:
                self._kv_log_exec.shutdown(wait=True)
                self._kv_log.sync()
                self._kv_log.close()
            except Exception:  # noqa: BLE001
                pass
            self._kv_log = None
        await self._pool.close_all()

    # ---- nodes ------------------------------------------------------------
    async def rpc_register_node(self, p):
        entry = _NodeEntry(p["node_id"], p["address"], p["resources"],
                           p.get("labels", {}))
        self.nodes[p["node_id"]] = entry
        return {"ok": True}

    async def rpc_heartbeat(self, p):
        f = C.maybe_fire("gcs.kill")
        if f is not None:
            self._record_failure(C.event_payload("gcs.kill", f))
            import os as _os

            if _os.environ.get("RT_NODE_DAEMON"):
                # standalone head daemon (rt start): die for real — but
                # snapshot FIRST so the injection event survives its own
                # kill (the restarted head replays the feed)
                self.mark_dirty()
                try:
                    self._persist_snapshot()
                except Exception:  # noqa: BLE001 — the kill still happens
                    pass
                asyncio.get_running_loop().call_later(0.1, _os._exit, 137)
            # in-process GCS (driver-hosted / test cluster): exiting would
            # kill the host process — the stamped event records the
            # suppression; tests use Cluster.kill_gcs() instead
        entry = self.nodes.get(p["node_id"])
        if entry is None:
            return {"ok": False, "unknown": True,
                    "chaos_rev": self._chaos_rev,
                    "chaos_armed": self._CHAOS_KEY in self.kv}
        entry.last_heartbeat = time.monotonic()
        resurrected = False
        if not entry.alive:
            # A heartbeat from a "dead" node proves the death was spurious —
            # on a loaded single-core host the shared event loop can stall
            # past node_death_timeout_s (a large pickle, a jit compile)
            # and the monitor then wins the post-stall race against the
            # queued heartbeat. Leaving the node dead wedges every future
            # actor/task placement (pick_node skips dead nodes forever).
            # The reference instead kills the raylet and has it re-register
            # under a new node id (gcs_node_manager.cc); an in-process
            # raylet can't restart, so resurrect it in place. The reply
            # flag tells the raylet to re-publish its object locations
            # (death dropped them from the directory).
            entry.alive = True
            resurrected = True
            self.mark_dirty()
        if "available" in p:
            entry.view.available = ResourceSet(p["available"])
        entry.queued_demands = p.get("queued_demands", [])
        # scheduler queue telemetry: depth of the raylet's pending-task
        # queue rides every heartbeat (feeds rt_raylet_queue_depth and the
        # nodes listing — the number that explains a 255 s probe latency)
        if "queue_depth" in p:
            entry.queue_depth = p["queue_depth"]
        if "sched" in p:
            # scheduling-plane snapshot (per-class depth/wait + warm-pool
            # occupancy/hit-rate): feeds `rt status`, the dashboard Nodes
            # tab and the `rt doctor` per-class starvation finding
            entry.sched = p["sched"]
        # chaos-plan revision + armed flag ride every heartbeat reply:
        # raylets compare against their last-seen rev and (re)fetch
        # @chaos/plan on change — the distribution path that lets
        # `rt chaos` torture a live cluster. The armed flag lets a DISARM
        # propagate without any KV fetch, so even a plan dropping every
        # other rpc stays disarmable.
        return {"ok": True, "resurrected": resurrected,
                "chaos_rev": self._chaos_rev,
                "chaos_armed": self._CHAOS_KEY in self.kv}

    async def rpc_cluster_load(self, p):
        """Autoscaler input: per-node capacity/usage + unplaced demand
        (reference: the load report behind resource_demand_scheduler)."""
        out = []
        for n in self.nodes.values():
            out.append({
                "node_id": n.node_id, "alive": n.alive,
                "labels": dict(n.view.labels),
                "total": n.view.total.to_dict(),
                "available": n.view.available.to_dict(),
                "queued_demands": getattr(n, "queued_demands", []),
            })
        # Unplaced placement-group bundles are cluster-level demand (PGs are
        # scheduled by the GCS, so they never sit in any raylet's queue);
        # ride them on a synthetic zero-capacity entry so the autoscaler
        # bin-packs gang reservations too — a pending slice_group() is
        # exactly what should provision a TPU pod slice (reference:
        # resource_demand_scheduler handles pending PGs the same way).
        pending = []
        for pg in self.placement_groups.values():
            if pg.state == PG_PENDING:
                for i, b in enumerate(pg.bundles):
                    if pg.bundle_nodes[i] is None:
                        d = {"resources": dict(b), "count": 1}
                        # STRICT_SPREAD bundles can never share a node —
                        # the autoscaler's bin-pack must know (else a gang
                        # that numerically fits one node never provisions).
                        if pg.strategy == "STRICT_SPREAD":
                            d["strict_spread_group"] = pg.pg_id
                        pending.append(d)
        if pending:
            out.append({
                "node_id": "@pending_pg_bundles", "alive": True,
                "labels": {}, "total": {}, "available": {},
                "queued_demands": pending[:100],
            })
        return out

    async def rpc_list_nodes(self, p):
        return [{
            "node_id": n.node_id, "address": n.address, "alive": n.alive,
            "resources": n.view.total.to_dict(),
            "available": n.view.available.to_dict(),
            "labels": dict(n.view.labels),
            "queue_depth": getattr(n, "queue_depth", 0),
            "sched": getattr(n, "sched", None),
            # dead rows persist for the cluster's lifetime: when + why the
            # node died lets `rt doctor` window its findings instead of
            # flagging a drain from hours ago as critical forever
            "death_t": getattr(n, "death_t", None),
            "death_reason": getattr(n, "death_reason", ""),
        } for n in self.nodes.values()]

    async def rpc_drain_node(self, p):
        entry = self.nodes.get(p["node_id"])
        if entry:
            await self._mark_node_dead(entry, "drained")
        return {"ok": True}

    async def _monitor_loop(self) -> None:
        cfg = get_config()
        started = time.monotonic()
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            now = time.monotonic()
            for entry in list(self.nodes.values()):
                if entry.alive and now - entry.last_heartbeat > cfg.node_death_timeout_s:
                    await self._mark_node_dead(entry, "heartbeat timeout")
            try:
                # per-tick cross-node balance sample: feeds the
                # rt_sched_node_imbalance gauge, `rt sched balance` and the
                # doctor's sustained-imbalance grading
                self._update_balance()
            except Exception:  # noqa: BLE001 — telemetry only
                pass
            # Restored-ALIVE actors whose node never (re-)registered: after a
            # grace window for surviving raylets to reattach (they re-register
            # under their old node id on an "unknown" heartbeat reply), the
            # worker is provably gone — run the normal failure path so the
            # restart budget can recreate the actor (reference: GCS FT
            # reconciliation of the actor table after restart).
            if now - started > cfg.node_death_timeout_s:
                for actor in list(self.actors.values()):
                    if (actor.state in (ACTOR_ALIVE,)
                            and actor.node_id is not None
                            and actor.node_id not in self.nodes):
                        await self._handle_actor_failure(
                            actor, F.cause_dict(
                                F.NODE_DEATH,
                                "node never re-registered after GCS "
                                "restart", node_id=actor.node_id))
            try:
                # pickle+write runs OFF the loop: a large table snapshot
                # must not stall heartbeat handling (and spuriously kill
                # nodes). Copies are taken on the loop; IO in the executor.
                if (self._persist_path
                        and self._persist_seq != self._persisted_seq):
                    seq = self._persist_seq
                    state = self._snapshot_tables()
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._write_snapshot, state)
                    self._persisted_seq = seq
            except Exception:
                pass

    async def _mark_node_dead(self, entry: _NodeEntry, reason: str) -> None:
        self.mark_dirty()  # internal transitions must persist too
        entry.alive = False
        entry.death_t = time.time()
        entry.death_reason = reason
        self._record_failure({
            "category": F.NODE_DEATH, "message": f"node died: {reason}",
            "node_id": entry.node_id, "address": entry.address})
        # Objects whose only copy was there are lost (lineage reconstruction
        # is a later round); actors there restart elsewhere if budgeted.
        for oid, locs in list(self.object_locations.items()):
            locs.discard(entry.node_id)
        for actor in list(self.actors.values()):
            if actor.node_id == entry.node_id and actor.state in (
                    ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                await self._handle_actor_failure(actor, F.cause_dict(
                    F.NODE_DEATH, f"node died: {reason}",
                    node_id=entry.node_id))
        # Reschedule ONLY the lost bundles of affected placement groups
        # (reference: GcsPlacementGroupManager PG rescheduling on node death).
        # Surviving bundles keep their reservations — actors/tasks inside
        # them are still running and hold chips from those reservations.
        # PENDING groups are cleared too (a second death mid-reschedule must
        # not leave the dead node's id pinned in bundle_nodes); their
        # already-running _schedule_pg loop replans the now-missing slots.
        for pg in self.placement_groups.values():
            if pg.state == PG_REMOVED or entry.node_id not in pg.bundle_nodes:
                continue
            was_created = pg.state == PG_CREATED
            pg.bundle_nodes = [None if nid == entry.node_id else nid
                               for nid in pg.bundle_nodes]
            if was_created:
                pg.state = PG_PENDING
                spawn_task(self._schedule_pg(pg))

    # ---- chaos plane (util/chaos.py) ---------------------------------------
    # The GCS is the plan's distribution point: arm stores the plan in the
    # KV (@chaos/plan) and bumps a revision that rides every heartbeat
    # reply; raylets fetch + arm on rev change and forward to their workers.

    _CHAOS_KEY = "@chaos/plan"

    async def rpc_chaos_arm(self, p):
        try:
            plan = C.ChaosPlan.from_value(p.get("plan"))
        except (ValueError, TypeError) as e:
            return {"error": str(e)}
        self._chaos_rev = self._chaos_rev + 1
        # fresh nonce per EXPLICIT arm: re-running the same plan repeats
        # the experiment (counters reset everywhere), while re-announces
        # of this stored copy (head restart, worker forwards) keep the
        # nonce and stay idempotent
        plan.nonce = self._chaos_rev
        await self.rpc_kv_put({"key": self._CHAOS_KEY,
                               "value": plan.to_json()})
        # arm this process too (gcs.kill / rpc.* sites in the GCS's own
        # clients; in-process clusters share the process with everything)
        C.arm(plan, rev=self._chaos_rev)
        return {"ok": True, "rev": self._chaos_rev,
                "plan": plan.to_dict()}

    async def rpc_chaos_disarm(self, p):
        await self.rpc_kv_del({"key": self._CHAOS_KEY})
        self._chaos_rev = self._chaos_rev + 1
        C.disarm()
        return {"ok": True, "rev": self._chaos_rev}

    async def rpc_chaos_status(self, p):
        raw = self.kv.get(self._CHAOS_KEY)
        plan = None
        if raw:
            try:
                plan = C.ChaosPlan.from_value(
                    raw.decode() if isinstance(raw, bytes) else raw).to_dict()
            except (ValueError, TypeError):
                plan = None
        return {"armed": plan is not None,
                "rev": self._chaos_rev, "plan": plan,
                "local": C.status()}

    # ---- kv / function table ----------------------------------------------
    async def rpc_kv_put(self, p):
        self.mark_dirty()
        self.kv[p["key"]] = p["value"]
        if self._kv_log is not None:
            # WAL append off-loop (native side releases the GIL during the
            # write); the single-thread executor keeps append order == the
            # order the table saw
            await asyncio.get_running_loop().run_in_executor(
                self._kv_log_exec, self._kv_put_durable, p["key"],
                self._encode_kv(p["value"]))
        return {"ok": True}

    def _kv_put_durable(self, key: str, value: bytes) -> None:
        """Runs on the WAL executor thread: append, and fsync when the
        operator asked for host-crash durability (RT_WAL_FSYNC=1)."""
        from ray_tpu._private.config import get_config

        self._kv_log.put(key, value)
        if get_config().wal_fsync:
            self._kv_log.sync()

    async def rpc_kv_get(self, p):
        return {"value": self.kv.get(p["key"])}

    async def rpc_kv_del(self, p):
        self.mark_dirty()
        self.kv.pop(p["key"], None)
        if self._kv_log is not None:
            await asyncio.get_running_loop().run_in_executor(
                self._kv_log_exec, self._kv_del_durable, p["key"])
        return {"ok": True}

    def _kv_del_durable(self, key: str) -> None:
        """WAL-executor thread: tombstone, honoring RT_WAL_FSYNC like puts —
        an un-fsynced acked delete resurrecting after a host crash breaks
        the same durability promise as a lost put."""
        from ray_tpu._private.config import get_config

        self._kv_log.delete(key)
        if get_config().wal_fsync:
            self._kv_log.sync()

    async def rpc_kv_keys(self, p):
        return {"keys": [k for k in self.kv if k.startswith(p["prefix"])]}

    # ---- object directory --------------------------------------------------
    async def rpc_add_object_location(self, p):
        self.mark_dirty()
        oid, node_id = p["oid"], p["node_id"]
        self.object_locations.setdefault(oid, set()).add(node_id)
        if "size" in p:
            self.object_sizes[oid] = p["size"]
        for fut in self._location_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return {"ok": True}

    async def rpc_remove_object_location(self, p):
        self.mark_dirty()
        locs = self.object_locations.get(p["oid"])
        if locs:
            locs.discard(p["node_id"])
        return {"ok": True}

    async def rpc_get_object_locations(self, p):
        oid = p["oid"]
        timeout = p.get("timeout")
        locs = self.object_locations.get(oid)
        if not locs and p.get("wait"):
            fut = asyncio.get_running_loop().create_future()
            self._location_waiters.setdefault(oid, []).append(fut)
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                pass
            locs = self.object_locations.get(oid)
        alive = [n for n in (locs or ()) if self.nodes.get(n) and self.nodes[n].alive]
        return {
            "locations": [{"node_id": n, "address": self.nodes[n].address}
                          for n in alive],
            "size": self.object_sizes.get(oid),
        }

    # ---- actors ------------------------------------------------------------
    async def rpc_register_actor(self, p):
        self.mark_dirty()
        spec = p["spec"]
        actor_id = spec["actor_id"]
        name, ns = spec.get("name"), spec.get("namespace", "default")
        if name is not None:
            existing = self.named_actors.get((ns, name))
            if existing is not None:
                if spec.get("get_if_exists"):
                    return {"actor_id": existing, "existing": True,
                            "info": self.actors[existing].info(),
                            "method_meta": self.actors[existing].spec.get("method_meta")}
                return {"error": f"actor name {name!r} taken in namespace {ns!r}"}
        entry = _ActorEntry(actor_id, spec)
        self.actors[actor_id] = entry
        if name is not None:
            self.named_actors[(ns, name)] = actor_id
        spawn_task(self._schedule_actor(entry))
        return {"actor_id": actor_id, "existing": False}

    async def _schedule_actor(self, entry: _ActorEntry,
                              backoff: float = 0.0) -> None:
        if backoff:
            await asyncio.sleep(backoff)
        req = ResourceSet(entry.spec.get("resources", {}))
        strategy = entry.spec.get("scheduling_strategy")
        pg_info = entry.spec.get("pg")
        deadline = time.monotonic() + 3600.0
        while time.monotonic() < deadline:
            if entry.state == ACTOR_DEAD:
                return  # killed while pending/restarting
            if pg_info is not None:
                node_id = await self._pg_bundle_node(pg_info, entry)
                if node_id is None:
                    if entry.state == ACTOR_DEAD:
                        return
                    await asyncio.sleep(0.2)
                    continue
            else:
                views = {nid: n.view for nid, n in self.nodes.items() if n.alive}
                node_id = pick_node(strategy, views, req)
            if node_id is None:
                await asyncio.sleep(0.2)  # infeasible now; wait for nodes
                continue
            node = self.nodes[node_id]
            # placement receipt: which candidates were considered and why
            # this node won (bundle pin for PG actors, strategy pick
            # otherwise). Create-side failures retry through this loop and
            # restamp; the store's dedup folds the repeats.
            self._record_placement({
                "kind": "actor_place",
                "actor_id": entry.actor_id,
                "name": entry.spec.get("class_name"),
                "node_id": node_id,
                "reason": ("pg_bundle" if pg_info is not None
                           else _strategy_kind(strategy)),
                "candidates": [self._node_features(nid) for nid in (
                    [node_id] if pg_info is not None else list(views)[:8])],
            })
            try:
                client = await self._pool.get(node.address)
                # Bounded: a wedged raylet must fail over to another node,
                # not pin this actor PENDING_CREATION forever (the raylet's
                # own create path is bounded by process_startup_timeout_s).
                cfg = get_config()
                create_timeout = (cfg.process_startup_timeout_s
                                  + (cfg.runtime_env_setup_timeout_s
                                     if entry.spec.get("runtime_env") else 0)
                                  + 30.0)
                restarts_before = entry.num_restarts
                reply = await client.call("create_actor", {
                    "actor_id": entry.actor_id, "spec": entry.spec},
                    timeout=create_timeout)
                if entry.state == ACTOR_DEAD:
                    # Killed during creation: reap the just-created worker.
                    if reply.get("ok"):
                        await client.call("kill_actor",
                                          {"actor_id": entry.actor_id})
                    return
                if reply.get("ok"):
                    # Don't clobber node_id once an ALIVE report landed — if
                    # a timed-out earlier attempt won the ALIVE race, THIS
                    # copy is the stale one (rpc_actor_update already killed
                    # it) and node_id must keep pointing at the winner.
                    if entry.state != ACTOR_ALIVE:
                        entry.node_id = node_id
                    return  # raylet reports actor_update(ALIVE) when ready
                if reply.get("retry"):
                    await asyncio.sleep(0.2)
                    continue
                if (entry.state == ACTOR_DEAD
                        or entry.num_restarts != restarts_before):
                    # the raylet reported this same death via actor_update
                    # BEFORE replying and _handle_actor_failure already
                    # scheduled a restart (num_restarts moved) or finalized
                    # — finalizing here would burn the restart budget the
                    # GCS just honored. A reply with NO matching
                    # actor_update (raylet spawn failure / startup timeout:
                    # its generic except path never updates) falls through,
                    # so the actor still dies loudly instead of wedging in
                    # RESTARTING forever.
                    return
                await self._finalize_actor_death(
                    entry, reply.get("cause") or F.cause_dict(
                        F.WORKER_CRASH,
                        reply.get("error", "creation failed"),
                        node_id=node_id))
                return
            except Exception:  # node unreachable or create timed out
                # If the create was merely SLOW (not dead), its worker may
                # still come up after we re-place the actor elsewhere —
                # best-effort kill so two live copies can never coexist
                # (rpc_actor_update's stale-ALIVE guard is the backstop).
                spawn_task(self._kill_stale_creation(node.address,
                                                     entry.actor_id))
                self._pool.invalidate(node.address)
                await asyncio.sleep(0.2)
        await self._finalize_actor_death(entry, F.cause_dict(
            F.SCHEDULING_TIMEOUT, "scheduling timed out"))

    async def _kill_stale_creation(self, address: str, actor_id: str) -> None:
        try:
            client = await self._pool.get(address)
            await client.call("kill_actor", {"actor_id": actor_id},
                              timeout=10)
        except Exception:  # noqa: BLE001 — node really is gone
            pass

    async def _pg_bundle_node(self, pg_info: Dict, entry: _ActorEntry
                              ) -> Optional[str]:
        """Resolve (and fix) the bundle an actor lands in; None = not ready."""
        pg = self.placement_groups.get(pg_info["pg_id"])
        if pg is None or pg.state == PG_REMOVED:
            await self._finalize_actor_death(entry, F.cause_dict(
                F.PG_REMOVED, "placement group removed",
                pg_id=pg_info.get("pg_id")))
            return None
        if pg.state != PG_CREATED:
            return None
        idx = pg_info.get("bundle_index", -1)
        if idx < 0:
            idx = pg._rr % len(pg.bundles)
            pg._rr += 1
            pg_info["bundle_index"] = idx  # pin for restarts
        entry.spec["pg"] = pg_info
        return pg.bundle_nodes[idx]

    async def rpc_actor_update(self, p):
        self.mark_dirty()
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"ok": False}
        state = p["state"]
        if state == ACTOR_ALIVE:
            stale_alive = (
                entry.state == ACTOR_DEAD
                # A second copy finishing creation after the scheduler timed
                # out and placed the actor elsewhere: the FIRST ALIVE wins,
                # the loser's worker is reaped (never two live copies).
                or (entry.state == ACTOR_ALIVE and entry.node_id is not None
                    and p.get("node_id") not in (None, entry.node_id)))
            if stale_alive:
                node = self.nodes.get(p.get("node_id", ""))
                if node is not None:
                    try:
                        client = await self._pool.get(node.address)
                        await client.call("kill_actor",
                                          {"actor_id": entry.actor_id})
                    except Exception:
                        pass
                return {"ok": True, "stale": True}
            entry.state = ACTOR_ALIVE
            entry.address = p.get("address")
            entry.node_id = p.get("node_id", entry.node_id)
            self._wake_actor_waiters(entry)
        elif state == ACTOR_DEAD:
            # Ignore death reports from a node that no longer owns the actor
            # (e.g. a resurrected node reaping its orphaned pre-death copy —
            # the restarted copy elsewhere is alive and well).
            reporter = p.get("node_id")
            if (reporter is not None and entry.node_id is not None
                    and reporter != entry.node_id):
                return {"ok": True, "stale": True}
            await self._handle_actor_failure(
                entry, p.get("cause") or F.cause_dict(
                    F.WORKER_CRASH, p.get("reason", "worker died"),
                    node_id=reporter))
        return {"ok": True}

    async def rpc_actor_unreachable(self, p):
        """A caller failed to CONNECT to an ALIVE actor's address. Verify
        before acting (the caller may just have a stale cache): if the
        actor's node is gone or dead, run the normal failure path so the
        restart budget applies — the fast lane for post-GCS-restart
        recovery, ahead of the monitor's grace window."""
        entry = self.actors.get(p["actor_id"])
        if (entry is None or entry.state != ACTOR_ALIVE
                or entry.address != p.get("address")):
            return {"ok": False}
        node = self.nodes.get(entry.node_id or "")
        if node is not None and node.alive:
            return {"ok": False}  # node looks fine; caller should retry
        await self._handle_actor_failure(entry, F.cause_dict(
            F.NODE_DEATH, "reported unreachable and its node is gone",
            node_id=entry.node_id))
        return {"ok": True}

    def _observe_actor_restart(self) -> None:
        """``rt_actor_restarts_total``: restarts the GCS scheduled after an
        actor worker died with budget left. Registry-local; shipped by the
        co-resident pusher (driver, or the head raylet's)."""
        try:
            from ray_tpu.util import metrics as M

            if not hasattr(self, "_restart_counter"):
                self._restart_counter = M.get_or_create(
                    M.Counter, "rt_actor_restarts_total",
                    "Actor restarts scheduled by the GCS after a failure")
            self._restart_counter.inc()
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    async def _handle_actor_failure(self, entry: _ActorEntry, reason) -> None:
        """``reason`` is a ``failure.py`` cause dict (legacy strings are
        coerced). With restart budget left the actor restarts and the
        failure is recorded with the underlying category; an exhausted
        budget re-categorizes the terminal event as restart-exhausted."""
        self.mark_dirty()
        if entry.state == ACTOR_DEAD:
            return
        cause = F.FailureCause.from_value(reason)
        max_restarts = entry.spec.get("max_restarts", 0)
        if entry.spec.get("_explicit_kill"):
            max_restarts = 0
        if max_restarts == -1 or entry.num_restarts < max_restarts:
            entry.num_restarts += 1
            entry.state = ACTOR_RESTARTING
            entry.address = None
            self._observe_actor_restart()
            self._record_failure({
                "category": cause.category, "message": str(cause),
                "actor_id": entry.actor_id,
                "name": entry.spec.get("class_name"),
                "node_id": cause.context.get("node_id", entry.node_id),
                "restarting": True, "num_restarts": entry.num_restarts})
            # Restart-storm damping: CONSECUTIVE restarts back off
            # exponentially (capped, jittered) instead of re-dispatching a
            # crash loop at a fixed 0.5s cadence. The streak — not the
            # lifetime num_restarts — keys the exponent, and it resets
            # once the actor stayed healthy past the cap: an isolated
            # failure of a long-lived actor recovers at base speed.
            # Recorded on the entry so `rt list actors` / tests see it.
            cfg = get_config()
            now = time.monotonic()
            if (now - getattr(entry, "last_failure_t", -1e9)
                    > cfg.actor_restart_backoff_max_s):
                entry.restart_streak = 0
            entry.restart_streak = getattr(entry, "restart_streak", 0) + 1
            entry.last_failure_t = now
            backoff = F.backoff_with_jitter(
                entry.restart_streak, cfg.actor_restart_backoff_s,
                cfg.actor_restart_backoff_max_s)
            entry.last_restart_backoff_s = backoff
            # Backoff happens inside the spawned task — this path runs on the
            # monitor loop and must not stall node-death handling.
            spawn_task(self._schedule_actor(entry, backoff=backoff))
        else:
            if entry.num_restarts >= max_restarts > 0:
                # the budget existed and is spent: the terminal cause is
                # the exhaustion itself; the last underlying cause rides
                # the message
                cause = F.FailureCause(
                    F.ACTOR_RESTART_EXHAUSTED,
                    f"out of restarts ({entry.num_restarts}/"
                    f"{max_restarts}); last failure: {cause}",
                    **cause.context)
            await self._finalize_actor_death(entry, cause)

    async def _finalize_actor_death(self, entry: _ActorEntry, reason) -> None:
        cause = F.FailureCause.from_value(reason)
        entry.state = ACTOR_DEAD
        entry.death_reason = str(cause)
        entry.death_cause = dict(
            cause.to_dict(), actor_id=entry.actor_id,
            num_restarts=entry.num_restarts,
            node_id=cause.context.get("node_id", entry.node_id),
            t=time.time())  # recency: rt doctor windows actor findings
        self._record_failure(dict(
            entry.death_cause, name=entry.spec.get("class_name")))
        name, ns = entry.spec.get("name"), entry.spec.get("namespace", "default")
        if name is not None and self.named_actors.get((ns, name)) == entry.actor_id:
            del self.named_actors[(ns, name)]
        self._wake_actor_waiters(entry)

    def _wake_actor_waiters(self, entry: _ActorEntry) -> None:
        for fut in entry.waiters:
            if not fut.done():
                fut.set_result(True)
        entry.waiters.clear()

    async def rpc_get_actor_info(self, p):
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"error": "unknown actor"}
        if p.get("wait_alive"):
            deadline = time.monotonic() + p.get("timeout", 60.0)
            while entry.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                fut = asyncio.get_running_loop().create_future()
                entry.waiters.append(fut)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(fut, remaining)
                except asyncio.TimeoutError:
                    break
        return {"info": entry.info(),
                "method_meta": entry.spec.get("method_meta")}

    async def rpc_get_named_actor(self, p):
        actor_id = self.named_actors.get((p.get("namespace", "default"), p["name"]))
        if actor_id is None:
            return {"error": f"no actor named {p['name']!r}"}
        entry = self.actors[actor_id]
        return {"actor_id": actor_id, "info": entry.info(),
                "method_meta": entry.spec.get("method_meta")}

    async def rpc_kill_actor(self, p):
        self.mark_dirty()
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"ok": False}
        entry.spec["_explicit_kill"] = True
        if entry.address and entry.node_id:
            node = self.nodes.get(entry.node_id)
            if node:
                try:
                    client = await self._pool.get(node.address)
                    await client.call("kill_actor", {"actor_id": entry.actor_id})
                except Exception:
                    pass
        await self._finalize_actor_death(entry, F.cause_dict(
            F.CANCELLED, "killed via kill()"))
        return {"ok": True}

    async def rpc_list_actors(self, p):
        return [a.info() for a in self.actors.values()]

    # ---- placement groups ---------------------------------------------------
    async def rpc_create_placement_group(self, p):
        self.mark_dirty()
        entry = _PgEntry(p["pg_id"], p["bundles"], p["strategy"],
                         p.get("name", ""))
        self.placement_groups[p["pg_id"]] = entry
        spawn_task(self._schedule_pg(entry))
        return {"ok": True}

    def _pg_plan(self, entry: _PgEntry) -> Optional[Dict[int, str]]:
        """Pick a node for every UNPLACED bundle under the strategy, against a
        scratch copy of the availability view (so multi-bundle fits are
        accounted). Already-placed bundles (partial reschedule after node
        death) constrain the plan but are not re-placed."""
        import copy

        views = {nid: copy.deepcopy(n.view) for nid, n in self.nodes.items()
                 if n.alive}
        reqs = [ResourceSet(b) for b in entry.bundles]
        missing = [i for i, nid in enumerate(entry.bundle_nodes) if nid is None]
        used_nodes: Set[str] = {nid for nid in entry.bundle_nodes if nid}
        plan: Dict[int, str] = {}
        if entry.strategy == "STRICT_PACK":
            total = ResourceSet()
            for i in missing:
                total = total.add(reqs[i])
            placed = next((n for n in entry.bundle_nodes if n), None)
            candidates = ([placed] if placed else list(views.keys()))
            for nid in candidates:
                if nid in views and views[nid].can_fit(total):
                    return {i: nid for i in missing}
            return None
        for i in missing:
            req = reqs[i]
            candidates = list(views.items())
            if entry.strategy in ("SPREAD", "STRICT_SPREAD"):
                fresh = [(nid, v) for nid, v in candidates if nid not in used_nodes]
                if entry.strategy == "STRICT_SPREAD":
                    candidates = fresh
                elif fresh:
                    candidates = fresh + [(n, v) for n, v in candidates
                                          if n in used_nodes]
            elif entry.strategy == "PACK" and used_nodes:
                candidates.sort(key=lambda kv: kv[0] not in used_nodes)
            chosen = None
            for nid, view in candidates:
                if view.can_fit(req):
                    chosen = nid
                    break
            if chosen is None:
                return None
            views[chosen].allocate(req)
            used_nodes.add(chosen)
            plan[i] = chosen
        return plan

    async def _schedule_pg(self, entry: _PgEntry) -> None:
        """2-phase commit: prepare every (missing) bundle, then commit all —
        atomic gang reservation (reference: prepare-all/commit-all in
        ``gcs_placement_group_scheduler.cc``)."""
        while entry.state == PG_PENDING:
            plan = self._pg_plan(entry)
            if plan is None:
                await asyncio.sleep(0.2)
                continue
            # `prepared` tracks every bundle a prepare RPC was *sent* for —
            # a lost reply may still have reserved resources on the raylet,
            # so the unwind must release those too (release is idempotent).
            prepared: List[Tuple[int, str]] = []
            confirmed: List[Tuple[int, str]] = []
            ok = True
            for i, nid in plan.items():
                try:
                    client = await self._pool.get(self.nodes[nid].address)
                    prepared.append((i, nid))
                    reply = await client.call("prepare_bundle", {
                        "pg_id": entry.pg_id, "bundle_index": i,
                        "resources": entry.bundles[i]})
                    if reply.get("ok"):
                        confirmed.append((i, nid))
                    else:
                        ok = False
                        break
                except Exception:
                    ok = False
                    break
            committed: List[Tuple[int, str]] = []
            if ok and entry.state == PG_PENDING:
                for i, nid in confirmed:
                    try:
                        client = await self._pool.get(self.nodes[nid].address)
                        await client.call("commit_bundle", {
                            "pg_id": entry.pg_id, "bundle_index": i})
                        committed.append((i, nid))
                    except Exception:
                        ok = False  # node died mid-commit: unwind and retry
                        break
            if not ok or entry.state != PG_PENDING:
                for i, nid in prepared:
                    try:
                        client = await self._pool.get(self.nodes[nid].address)
                        await client.call("release_bundle", {
                            "pg_id": entry.pg_id, "bundle_index": i})
                    except Exception:
                        pass
                if entry.state != PG_PENDING:
                    return
                await asyncio.sleep(0.2)
                continue
            for i, nid in committed:
                entry.bundle_nodes[i] = nid
            # Re-check liveness AFTER recording placements: a node that died
            # while this loop was committing other bundles was invisible to
            # the death handler (its slot wasn't in bundle_nodes yet), so
            # null those slots here and let the replan below pick them up.
            for i, nid in committed:
                node = self.nodes.get(nid)
                if node is None or not node.alive:
                    entry.bundle_nodes[i] = None
            if any(nid is None for nid in entry.bundle_nodes):
                # A node holding an already-placed bundle died while this
                # iteration was preparing/committing (the death handler nulls
                # the slot but spawns no new loop for PENDING groups) —
                # replan the now-missing slots before declaring CREATED.
                await asyncio.sleep(0.2)
                continue
            entry.state = PG_CREATED
            self.mark_dirty()
            # placement receipt: one record per gang commit — gang_place
            # for multi-bundle groups (the TPU slice_group case), pg_place
            # for a single reserved bundle — with the committed
            # bundle→node map as the decision payload
            self._record_placement({
                "kind": ("gang_place" if len(entry.bundles) > 1
                         else "pg_place"),
                "pg_id": entry.pg_id,
                "name": entry.name,
                "node_id": next((n for n in entry.bundle_nodes if n), None),
                "reason": str(entry.strategy or "PACK").lower(),
                "bundle_nodes": list(entry.bundle_nodes),
                "candidates": [self._node_features(nid) for nid in
                               dict.fromkeys(n for n in entry.bundle_nodes
                                             if n)],
            })
            for fut in entry.waiters:
                if not fut.done():
                    fut.set_result(True)
            entry.waiters.clear()
            return

    async def rpc_wait_placement_group(self, p):
        entry = self.placement_groups.get(p["pg_id"])
        if entry is None:
            return {"error": "unknown placement group"}
        deadline = time.monotonic() + p.get("timeout", 3600.0)
        while entry.state == PG_PENDING and time.monotonic() < deadline:
            fut = asyncio.get_running_loop().create_future()
            entry.waiters.append(fut)
            try:
                await asyncio.wait_for(fut, deadline - time.monotonic())
            except asyncio.TimeoutError:
                break
        return {"state": entry.state}

    async def rpc_get_placement_group(self, p):
        entry = self.placement_groups.get(p["pg_id"])
        if entry is None:
            return {"error": "unknown placement group"}
        info = entry.info()
        if p.get("pick_bundle") and entry.state == PG_CREATED:
            idx = p.get("bundle_index", -1)
            if idx < 0:
                idx = entry._rr % len(entry.bundles)
                entry._rr += 1
            nid = entry.bundle_nodes[idx]
            info["picked_bundle"] = idx
            info["picked_address"] = (self.nodes[nid].address
                                      if nid in self.nodes else None)
        return info

    async def rpc_remove_placement_group(self, p):
        self.mark_dirty()
        entry = self.placement_groups.get(p["pg_id"])
        if entry is None:
            return {"ok": False}
        entry.state = PG_REMOVED
        for fut in entry.waiters:
            if not fut.done():
                fut.set_result(True)
        entry.waiters.clear()
        # Kill actors living in this PG's bundles BEFORE the bundle resources
        # (and chip assignments) are returned to the nodes — otherwise the
        # next scheduled task shares chips with a still-running actor
        # (reference: PG removal destroys all actors/tasks in the group).
        for actor in list(self.actors.values()):
            actor_pg = (actor.spec or {}).get("pg") or {}
            if actor_pg.get("pg_id") != entry.pg_id:
                continue
            if actor.state not in (ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                continue
            actor.spec["_explicit_kill"] = True
            if actor.node_id and actor.node_id in self.nodes:
                try:
                    client = await self._pool.get(
                        self.nodes[actor.node_id].address)
                    await client.call("kill_actor", {"actor_id": actor.actor_id})
                except Exception:
                    pass
            await self._finalize_actor_death(actor, F.cause_dict(
                F.PG_REMOVED, "placement group removed",
                pg_id=entry.pg_id))
        for i, nid in enumerate(entry.bundle_nodes):
            if nid is None or nid not in self.nodes:
                continue
            try:
                client = await self._pool.get(self.nodes[nid].address)
                await client.call("release_bundle", {
                    "pg_id": entry.pg_id, "bundle_index": i})
            except Exception:
                pass
        return {"ok": True}

    async def rpc_list_placement_groups(self, p):
        return [e.info() for e in self.placement_groups.values()]

    # ---- task routing (spillback target selection) -------------------------
    # ---- task events (reference: GcsTaskManager, gcs_task_manager.h:61 —
    # a bounded in-memory event store behind the State API) -----------------
    _TASK_EVENTS_CAP = 10000
    _STEP_EVENTS_CAP = 4096
    _SERVE_EVENTS_CAP = 4096
    _RECORDER_EVENTS_CAP = 4096

    #: payload keys of flight-recorder events (engine ticks/requests,
    #: rlhf pipeline iterations) — opaque to the GCS, rendered into
    #: timeline lanes client-side (util/timeline.py)
    _RECORDER_KEYS = ("engine_tick", "engine_request", "rlhf_iter")

    async def rpc_task_event(self, p):
        self._apply_task_event(p)
        return {"ok": True}

    async def rpc_task_events(self, p):
        """Batched form: the step profiler drains its whole ring in ONE
        call instead of a round-trip per record."""
        for ev in p.get("events") or ():
            self._apply_task_event(ev)
        return {"ok": True, "count": len(p.get("events") or ())}

    def _apply_task_event(self, p):
        if p.get("placement") is not None:
            # placement receipts ride the coalesced task_events channel
            # (one batched drain, no second RPC path) but land in their own
            # bounded deduping store — a dispatch flood must never evict
            # real task history
            self._record_placement(p["placement"])
            return
        if not hasattr(self, "task_events"):
            from collections import OrderedDict

            self.task_events: "OrderedDict[str, Dict]" = OrderedDict()
            # step-profiler records get their OWN bounded store: a streamed
            # profile run emits a record per token, and sharing the task
            # FIFO would evict the real task history
            self.step_events: "OrderedDict[str, Dict]" = OrderedDict()
            # serve request spans likewise (serve/obs.py): heavy traffic
            # emits several spans per request and must not crowd out tasks
            self.serve_events: "OrderedDict[str, Dict]" = OrderedDict()
            # flight-recorder events (engine ticks/requests, rlhf
            # iterations) likewise: a busy engine drains up to 256 ticks
            # per cadence and would flush the real task history
            self.recorder_events: "OrderedDict[str, Dict]" = OrderedDict()
        is_step = p.get("profile") is not None
        is_serve = str(p.get("task_id", "")).startswith("serve:")
        is_recorder = any(p.get(k) is not None for k in self._RECORDER_KEYS)
        if is_step:
            store, cap = self.step_events, self._STEP_EVENTS_CAP
        elif is_recorder:
            store, cap = self.recorder_events, self._RECORDER_EVENTS_CAP
        elif is_serve:
            store, cap = self.serve_events, self._SERVE_EVENTS_CAP
        else:
            store, cap = self.task_events, self._TASK_EVENTS_CAP
        ev = store.pop(p["task_id"], None)
        if ev is None and p.get("state") is None:
            # a phases-only partial for a task the FIFO already evicted:
            # don't resurrect a skeleton row (and evict a live event)
            return
        ev = ev or {}
        # Partial merges (a driver's phases-only update) omit state/node_id
        # and must not clobber what the raylet recorded — a FAILED task
        # stays FAILED and keeps its node.
        ev.update({"task_id": p["task_id"], "name": p.get("name", ev.get("name")),
                   "state": p.get("state", ev.get("state")),
                   "node_id": p.get("node_id", ev.get("node_id")),
                   "updated_at": time.time()})
        if p.get("trace") is not None:
            ev["trace"] = p["trace"]
        # per-phase latency breakdown: the raylet, the executing worker and
        # the driver each report the phases they own; the union accumulates
        # on the one event (tracing.PHASE_ORDER documents the partition)
        if p.get("phases"):
            ev.setdefault("phases", {}).update(p["phases"])
        if p.get("worker_source") is not None:
            ev["worker_source"] = p["worker_source"]
        # spillback hop chain (from-node → to-node → reason) joins the
        # task's trace: `rt trace` renders it on the spillback phase row.
        # Bounded — spillback_max_hops caps real chains far below this.
        if p.get("spill_hop"):
            hops = ev.setdefault("spill_hops", [])
            if len(hops) < 8:
                hops.append(p["spill_hop"])
        # step-profiler records ride the same store: a breakdown payload
        # plus caller-supplied span times (the profiler measured the real
        # start/end; server receive-time would misplace the lane)
        if p.get("profile") is not None:
            ev["profile"] = p["profile"]
        for key in self._RECORDER_KEYS:
            if p.get(key) is not None:
                ev[key] = p[key]
        # per-state transition times feed ray_tpu.timeline()'s Chrome trace
        if p.get("times"):
            ev.setdefault("times", {}).update(p["times"])
        elif p.get("state"):
            ev.setdefault("times", {})[p["state"]] = time.time()
        store[p["task_id"]] = ev
        while len(store) > cap:
            store.popitem(last=False)

    async def rpc_list_tasks(self, p):
        # "profile": "only" -> step-profiler records (the Steps page);
        # "include" -> both lanes (the Perfetto timeline asks for this
        # explicitly); default EXCLUDES step records so legacy callers
        # (rt list tasks, the /metrics rt_tasks scrape, tracing) keep
        # seeing real tasks only. "serve": "include" additionally returns
        # the serve request spans (rt trace and the timeline ask for them;
        # the state API / dashboard Tasks tab stay real-tasks-only).
        mode = p.get("profile") or "exclude"
        limit = p.get("limit") or 1000
        events = []
        # limit applies PER STORE: a step store at its cap must not crowd
        # the real task events out of a combined (timeline) response
        if mode != "only":
            events += list(getattr(self, "task_events", {}).values())[-limit:]
        if mode != "exclude":
            events += list(getattr(self, "step_events", {}).values())[-limit:]
            # flight-recorder lanes ride the same opt-in: only the
            # timeline (profile "include") wants them — the state API,
            # `rt list tasks`, and the Steps page must not see
            # engtick/engreq/rlhfit pseudo-tasks
            if mode == "include":
                events += list(
                    getattr(self, "recorder_events", {}).values())[-limit:]
        if p.get("serve") == "include" and mode != "only":
            events += list(
                getattr(self, "serve_events", {}).values())[-limit:]
        return events

    # ---- serve events (autoscaler decision records; the store behind the
    # timeline's serve lane and `rt serve status --verbose`) --------------
    _SERVE_DECISIONS_CAP = 1024

    async def rpc_serve_event(self, p):
        if not hasattr(self, "serve_decisions"):
            from collections import deque

            self.serve_decisions: "deque" = deque(
                maxlen=self._SERVE_DECISIONS_CAP)
        p.setdefault("t", time.time())
        self.serve_decisions.append(p)
        return {"ok": True}

    async def rpc_list_serve_events(self, p):
        limit = p.get("limit") or 200
        events = list(getattr(self, "serve_decisions", ()))
        return events[-limit:]

    # ---- placement events (scheduling decision receipts: the store behind
    # `rt sched decisions`, `/api/sched` and the timeline's placement lane;
    # the instrument-first layer ROADMAP item 1's learned-placement work
    # scores against — Placeto-style features, recorded not discarded) -----
    _PLACEMENT_EVENTS_CAP = 2048
    _PLACEMENT_DEDUP_WINDOW_S = 5.0
    PLACEMENT_KINDS = ("dispatch_local", "spillback", "actor_place",
                       "pg_place", "warm_adopt", "gang_place")

    def _record_placement(self, p: Dict) -> None:
        """Store one placement decision record. Repeated identical decisions
        (same kind/node/reason/name) inside the dedup window collapse into
        the existing record's ``count`` — a 5k-task flood of local
        dispatches folds into one row instead of evicting the rest of the
        feed — and every report, deduped or not, increments
        ``rt_sched_placement_decisions_total{kind=}`` exactly once, here
        (single counting site: emitters never double-count)."""
        if not hasattr(self, "placement_events"):
            from collections import deque

            # GCS runs a single asyncio loop; these are loop-only (no lock)
            self.placement_events: "deque" = deque(
                maxlen=self._PLACEMENT_EVENTS_CAP)
            self._placement_last: Dict[Tuple, Dict] = {}
            self._placement_seq = 0
        p.setdefault("t", time.time())
        kind = p.setdefault("kind", "unknown")
        self._observe_placement(kind, p.get("hops"))
        # task_id deliberately NOT in the key: same-shaped decisions fold
        # into one row (count=N, first ids kept)
        key = (kind, p.get("node_id"), p.get("reason"), p.get("name"))
        last = self._placement_last.get(key)
        if (last is not None
                and p["t"] - last.get("last_t", last["t"])
                <= self._PLACEMENT_DEDUP_WINDOW_S):
            last["count"] = last.get("count", 1) + 1
            last["last_t"] = p["t"]
            # keep the freshest candidate features on the folded row — the
            # point of the record is the scheduler's CURRENT view
            if p.get("candidates"):
                last["candidates"] = p["candidates"]
            if (not self.placement_events
                    or last["seq"] < self.placement_events[0]["seq"]):
                self._placement_seq += 1
                last["seq"] = self._placement_seq
                self.placement_events.append(last)
            return
        p.setdefault("count", 1)
        self._placement_seq += 1
        p["seq"] = self._placement_seq
        self.placement_events.append(p)
        self._placement_last[key] = p
        if len(self._placement_last) > 2 * self._PLACEMENT_EVENTS_CAP:
            cutoff = p["t"] - self._PLACEMENT_DEDUP_WINDOW_S
            kept = {k: e for k, e in self._placement_last.items()
                    if e.get("last_t", e["t"]) > cutoff}
            if len(kept) > self._PLACEMENT_EVENTS_CAP:
                kept = dict(sorted(
                    kept.items(),
                    key=lambda kv: kv[1].get("last_t", kv[1]["t"])
                )[-self._PLACEMENT_EVENTS_CAP:])
            self._placement_last = kept

    def _observe_placement(self, kind: str, hops) -> None:
        """Decision counter + spillback-hop histogram. Registry-local;
        shipped by the co-resident pusher (driver, or the head raylet's)."""
        try:
            from ray_tpu.util import metrics as M

            if not hasattr(self, "_placement_counter"):
                self._placement_counter = M.get_or_create(
                    M.Counter, "rt_sched_placement_decisions_total",
                    "Placement decisions recorded, by decision kind",
                    tag_keys=("kind",))
                self._spillback_hops_hist = M.get_or_create(
                    M.Histogram, "rt_sched_spillback_hops",
                    "Spillback hops a task took before dispatching",
                    boundaries=(1.0, 2.0, 3.0, 5.0, 8.0))
            self._placement_counter.inc(1, {"kind": kind})
            if kind == "spillback" and hops:
                self._spillback_hops_hist.observe(float(hops))
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def _node_features(self, nid: str) -> Dict[str, Any]:
        """Per-node scheduling feature vector for a placement receipt's
        candidate set (queue state, warm pool, resource headroom — from the
        node's last heartbeat ``sched`` summary): the inputs a learned
        placement policy would score."""
        n = self.nodes.get(nid)
        if n is None:
            return {"node_id": nid}
        sched = getattr(n, "sched", None) or {}
        classes = sched.get("classes") or []
        warm = sched.get("warm") or {}
        return {
            "node_id": nid,
            "queue_depth": getattr(n, "queue_depth", 0),
            "running": sched.get("running", 0),
            "oldest_wait_s": round(max(
                (c.get("oldest_wait_s") or 0.0 for c in classes),
                default=0.0), 3),
            "warm_idle": warm.get("idle", 0),
            "headroom": n.view.available.to_dict(),
        }

    async def rpc_placement_event(self, p):
        self._record_placement(p)
        return {"ok": True}

    async def rpc_list_placement_events(self, p):
        events = list(getattr(self, "placement_events", ()))
        kind = p.get("kind")
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        node = p.get("node")
        if node:  # prefix match on chosen OR origin node (spillback hops)
            events = [e for e in events
                      if str(e.get("node_id") or "").startswith(node)
                      or str(e.get("from_node") or "").startswith(node)]
        since = p.get("since")
        if since:
            events = [e for e in events
                      if e.get("last_t", e.get("t", 0)) >= since]
        limit = p.get("limit") or 200
        return events[-limit:]

    # ---- cross-node balance telemetry (rt_sched_node_imbalance) ----------
    _BALANCE_HIST_CAP = 128

    def _update_balance(self) -> None:
        """Sample cross-node imbalance: the coefficient of variation over
        per-node queued+running load from the heartbeat ``sched``
        summaries. Called each monitor tick; ROADMAP item 1's bar is this
        series trending flat."""
        rows = []
        for n in self.nodes.values():
            if not n.alive:
                continue
            sched = getattr(n, "sched", None) or {}
            queued = getattr(n, "queue_depth", 0) or 0
            running = sched.get("running", 0) or 0
            rows.append({"node_id": n.node_id, "queued": queued,
                         "running": running, "load": queued + running})
        cov = imbalance_cov([r["load"] for r in rows])
        self._balance_now = {"cov": round(cov, 4), "nodes": rows}
        if not hasattr(self, "_balance_hist"):
            from collections import deque

            self._balance_hist: "deque" = deque(
                maxlen=self._BALANCE_HIST_CAP)
        self._balance_hist.append(
            {"t": time.time(), "cov": round(cov, 4),
             "loads": {r["node_id"]: r["load"] for r in rows}})
        try:
            from ray_tpu.util import metrics as M

            if not hasattr(self, "_imbalance_gauge"):
                # Registry-local; shipped by the co-resident pusher
                self._imbalance_gauge = M.get_or_create(
                    M.Gauge, "rt_sched_node_imbalance",
                    "Coefficient of variation of per-node queued+running "
                    "load (0 = balanced)")
            self._imbalance_gauge.set(cov)
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    async def rpc_sched_balance(self, p):
        """Balance snapshot + recent per-tick history: `rt sched balance`,
        `/api/sched` and the doctor's sustained-imbalance grading."""
        snap = getattr(self, "_balance_now", None)
        if snap is None:
            self._update_balance()
            snap = self._balance_now
        limit = p.get("limit") or 60
        return {"cov": snap["cov"], "nodes": snap["nodes"],
                "history": list(getattr(self, "_balance_hist", ()))[-limit:]}

    # ---- serve proxy registry (multi-proxy front doors): the controller
    # registers every HTTP proxy it starts so load balancers / `rt serve
    # status` / the dashboard can enumerate ingress endpoints without a
    # serve driver attached --------------------------------------------------
    _SERVE_PROXIES_CAP = 256

    async def rpc_serve_proxy_register(self, p):
        if not hasattr(self, "serve_proxies"):
            self.serve_proxies: Dict[str, Dict[str, Any]] = {}
        pid = str(p.get("proxy_id") or "")
        if not pid:
            return {"ok": False, "error": "proxy_id required"}
        self.serve_proxies[pid] = {
            "proxy_id": pid, "host": p.get("host"), "port": p.get("port"),
            "registered_at": time.time()}
        while len(self.serve_proxies) > self._SERVE_PROXIES_CAP:
            self.serve_proxies.pop(next(iter(self.serve_proxies)))
        return {"ok": True, "count": len(self.serve_proxies)}

    async def rpc_serve_proxy_deregister(self, p):
        """``proxy_id: "*"`` clears the registry (serve shutdown)."""
        reg = getattr(self, "serve_proxies", None)
        if not reg:
            return {"ok": True, "count": 0}
        pid = str(p.get("proxy_id") or "")
        if pid == "*":
            reg.clear()
        else:
            reg.pop(pid, None)
        return {"ok": True, "count": len(reg)}

    async def rpc_list_serve_proxies(self, p):
        return list(getattr(self, "serve_proxies", {}).values())

    # ---- memory events (spill / restore / oom_kill instants; the store
    # behind `rt memory --oom` and the timeline's memory lane) -------------
    _MEM_EVENTS_CAP = 2048

    async def rpc_mem_event(self, p):
        if not hasattr(self, "mem_events"):
            from collections import deque

            self.mem_events: "deque" = deque(maxlen=self._MEM_EVENTS_CAP)
        p.setdefault("t", time.time())
        self.mem_events.append(p)
        return {"ok": True}

    async def rpc_list_mem_events(self, p):
        events = list(getattr(self, "mem_events", ()))
        kind = p.get("kind")
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        limit = p.get("limit") or 1000
        return events[-limit:]

    # ---- failure events (the death-cause feed behind `rt errors`,
    # `/api/errors` and the timeline's errors lane; reference: the
    # error-info pubsub channel + RayErrorInfo in common.proto) ------------
    _FAILURE_EVENTS_CAP = 2048
    _FAILURE_DEDUP_WINDOW_S = 30.0

    def _record_failure(self, p: Dict) -> None:
        """Store one categorized FailureEvent. Repeated identical causes
        within the dedup window collapse into the existing event's
        ``count`` (a crash loop must not evict the rest of the feed), and
        every report — deduped or not — increments
        ``rt_failures_total{category=}`` exactly once, here (single
        counting site: emitters never double-count)."""
        if not hasattr(self, "failure_events"):
            from collections import deque

            self.failure_events: "deque" = deque(
                maxlen=self._FAILURE_EVENTS_CAP)
            self._failure_last: Dict[Tuple, Dict] = {}
            self._failure_seq = 0
        p.setdefault("t", time.time())
        p.setdefault("category", F.UNKNOWN)
        F.observe_failure(p["category"])
        # task_id deliberately NOT in the key: 5000 tasks failing the same
        # way within the window fold into one row (count=5000, first
        # task_id kept) instead of evicting the rest of the feed
        key = (p.get("category"), p.get("node_id"), p.get("actor_id"),
               p.get("name"), p.get("message"))
        last = self._failure_last.get(key)
        if (last is not None
                and p["t"] - last.get("last_t", last["t"])
                <= self._FAILURE_DEDUP_WINDOW_S):
            last["count"] = last.get("count", 1) + 1
            last["last_t"] = p["t"]
            # the deque may have rotated this row out while its crash loop
            # kept the dedup key warm — re-append (same dict, accrued
            # count) so an ONGOING failure stays visible in the feed
            if (not self.failure_events
                    or last["seq"] < self.failure_events[0]["seq"]):
                self._failure_seq += 1
                last["seq"] = self._failure_seq
                self.failure_events.append(last)
            return
        p.setdefault("count", 1)
        self._failure_seq += 1
        p["seq"] = self._failure_seq
        self.failure_events.append(p)
        self._failure_last[key] = p
        if len(self._failure_last) > 2 * self._FAILURE_EVENTS_CAP:
            # drop tracking for events long rotated out of the deque; if a
            # unique-key burst keeps everything inside the window, hard-cap
            # to the newest half so the prune actually shrinks (never an
            # O(n) rebuild per insert on the GCS loop)
            cutoff = p["t"] - self._FAILURE_DEDUP_WINDOW_S
            kept = {k: e for k, e in self._failure_last.items()
                    if e.get("last_t", e["t"]) > cutoff}
            if len(kept) > self._FAILURE_EVENTS_CAP:
                kept = dict(sorted(
                    kept.items(),
                    key=lambda kv: kv[1].get("last_t", kv[1]["t"])
                )[-self._FAILURE_EVENTS_CAP:])
            self._failure_last = kept

    async def rpc_failure_event(self, p):
        self._record_failure(p)
        return {"ok": True}

    async def rpc_list_failure_events(self, p):
        events = list(getattr(self, "failure_events", ()))
        category = p.get("category")
        if category:
            events = [e for e in events if e.get("category") == category]
        origin = p.get("origin")
        if origin == "organic":  # everything NOT injected by the chaos plane
            events = [e for e in events if not e.get("origin")]
        elif origin:
            events = [e for e in events if e.get("origin") == origin]
        since = p.get("since")
        if since:
            events = [e for e in events
                      if e.get("last_t", e.get("t", 0)) >= since]
        limit = p.get("limit") or 1000
        return events[-limit:]

    async def rpc_list_objects(self, p):
        limit = p.get("limit") or 1000
        out = []
        for oid, locs in list(self.object_locations.items())[:limit]:
            out.append({"object_id": oid,
                        "size": self.object_sizes.get(oid, 0),
                        "locations": sorted(locs)})
        return out

    async def rpc_route_task(self, p):
        req = ResourceSet(p["resources"])
        exclude = set(p.get("exclude") or ())
        views = {nid: n.view for nid, n in self.nodes.items()
                 if n.alive and nid not in exclude}
        if p.get("require_available"):
            # load-based spillback: only nodes that can run the task NOW
            # (by their last-heartbeat view) are acceptable targets
            views = {nid: v for nid, v in views.items() if v.can_fit(req)}
            if not views:
                return {"node_id": None}
        node_id = pick_node(p.get("strategy"), views, req,
                            preferred=p.get("preferred"))
        if node_id is None:
            return {"error": "infeasible", "node_id": None}
        reply = {"node_id": node_id, "address": self.nodes[node_id].address}
        if p.get("features"):
            # spillback receipts: ship the considered candidates' feature
            # vectors back so the origin raylet can stamp a truthful
            # record. Bounded — a wide cluster must not turn every route
            # reply into a telemetry payload.
            reply["candidates"] = [self._node_features(nid)
                                   for nid in list(views)[:8]]
        return reply

    # ---- cluster info -------------------------------------------------------
    async def rpc_cluster_resources(self, p):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.view.total.to_dict().items():
                total[k] = total.get(k, 0) + v
            for k, v in n.view.available.to_dict().items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def rpc_next_job_id(self, p):
        self.mark_dirty()
        self._job_counter += 1
        return {"job_index": self._job_counter}
