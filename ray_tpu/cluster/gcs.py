"""GCS: the cluster control plane (head-node metadata + actor lifecycle).

Reference analog: ``src/ray/gcs/gcs_server/`` — node membership + health
(``GcsNodeManager``, ``GcsHealthCheckManager``), actor lifecycle + restart
(``GcsActorManager``/``GcsActorScheduler``), internal KV (``GcsKvManager``,
also the function table), the object directory, and named actors. State is
in-memory (a Redis-backed store client is a later round's HA concern).

Long-poll futures replace the reference's pubsub channels for the two hot
subscriptions (actor-alive, object-location): O(#waiters) wakeups, no
polling.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import get_config
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.cluster.rpc import ConnectionPool
from ray_tpu.scheduler.policy import pick_node

ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class _NodeEntry:
    def __init__(self, node_id: str, address: str, resources: Dict[str, float],
                 labels: Dict[str, str]):
        self.node_id = node_id
        self.address = address
        self.view = NodeResources(resources, labels)
        self.alive = True
        self.last_heartbeat = time.monotonic()


class _ActorEntry:
    def __init__(self, actor_id: str, spec: Dict[str, Any]):
        self.actor_id = actor_id
        self.spec = spec                      # picklable creation spec
        self.state = ACTOR_PENDING
        self.address: Optional[str] = None
        self.node_id: Optional[str] = None
        self.num_restarts = 0
        self.death_reason = ""
        self.waiters: List[asyncio.Future] = []

    def info(self) -> Dict[str, Any]:
        return {
            "actor_id": self.actor_id, "state": self.state,
            "address": self.address, "node_id": self.node_id,
            "name": self.spec.get("name"), "namespace": self.spec.get("namespace"),
            "class_name": self.spec.get("class_name"),
            "num_restarts": self.num_restarts,
            "death_reason": self.death_reason,
            "max_task_retries": self.spec.get("max_task_retries", 0),
        }


class GcsServer:
    def __init__(self):
        self.nodes: Dict[str, _NodeEntry] = {}
        self.kv: Dict[str, bytes] = {}
        self.actors: Dict[str, _ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}
        self.object_locations: Dict[str, Set[str]] = {}
        self.object_sizes: Dict[str, int] = {}
        self._location_waiters: Dict[str, List[asyncio.Future]] = {}
        self._pool = ConnectionPool(peer_id="gcs")
        self._monitor_task: Optional[asyncio.Task] = None
        self._job_counter = 0

    def start_monitor(self) -> None:
        self._monitor_task = asyncio.ensure_future(self._monitor_loop())

    # ---- nodes ------------------------------------------------------------
    async def rpc_register_node(self, p):
        entry = _NodeEntry(p["node_id"], p["address"], p["resources"],
                           p.get("labels", {}))
        self.nodes[p["node_id"]] = entry
        return {"ok": True}

    async def rpc_heartbeat(self, p):
        entry = self.nodes.get(p["node_id"])
        if entry is None:
            return {"ok": False, "unknown": True}
        entry.last_heartbeat = time.monotonic()
        if "available" in p:
            entry.view.available = ResourceSet(p["available"])
        return {"ok": True}

    async def rpc_list_nodes(self, p):
        return [{
            "node_id": n.node_id, "address": n.address, "alive": n.alive,
            "resources": n.view.total.to_dict(),
            "available": n.view.available.to_dict(),
            "labels": dict(n.view.labels),
        } for n in self.nodes.values()]

    async def rpc_drain_node(self, p):
        entry = self.nodes.get(p["node_id"])
        if entry:
            await self._mark_node_dead(entry, "drained")
        return {"ok": True}

    async def _monitor_loop(self) -> None:
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            now = time.monotonic()
            for entry in list(self.nodes.values()):
                if entry.alive and now - entry.last_heartbeat > cfg.node_death_timeout_s:
                    await self._mark_node_dead(entry, "heartbeat timeout")

    async def _mark_node_dead(self, entry: _NodeEntry, reason: str) -> None:
        entry.alive = False
        # Objects whose only copy was there are lost (lineage reconstruction
        # is a later round); actors there restart elsewhere if budgeted.
        for oid, locs in list(self.object_locations.items()):
            locs.discard(entry.node_id)
        for actor in list(self.actors.values()):
            if actor.node_id == entry.node_id and actor.state in (
                    ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                await self._handle_actor_failure(actor, f"node died: {reason}")

    # ---- kv / function table ----------------------------------------------
    async def rpc_kv_put(self, p):
        self.kv[p["key"]] = p["value"]
        return {"ok": True}

    async def rpc_kv_get(self, p):
        return {"value": self.kv.get(p["key"])}

    async def rpc_kv_del(self, p):
        self.kv.pop(p["key"], None)
        return {"ok": True}

    async def rpc_kv_keys(self, p):
        return {"keys": [k for k in self.kv if k.startswith(p["prefix"])]}

    # ---- object directory --------------------------------------------------
    async def rpc_add_object_location(self, p):
        oid, node_id = p["oid"], p["node_id"]
        self.object_locations.setdefault(oid, set()).add(node_id)
        if "size" in p:
            self.object_sizes[oid] = p["size"]
        for fut in self._location_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)
        return {"ok": True}

    async def rpc_remove_object_location(self, p):
        locs = self.object_locations.get(p["oid"])
        if locs:
            locs.discard(p["node_id"])
        return {"ok": True}

    async def rpc_get_object_locations(self, p):
        oid = p["oid"]
        timeout = p.get("timeout")
        locs = self.object_locations.get(oid)
        if not locs and p.get("wait"):
            fut = asyncio.get_running_loop().create_future()
            self._location_waiters.setdefault(oid, []).append(fut)
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                pass
            locs = self.object_locations.get(oid)
        alive = [n for n in (locs or ()) if self.nodes.get(n) and self.nodes[n].alive]
        return {
            "locations": [{"node_id": n, "address": self.nodes[n].address}
                          for n in alive],
            "size": self.object_sizes.get(oid),
        }

    # ---- actors ------------------------------------------------------------
    async def rpc_register_actor(self, p):
        spec = p["spec"]
        actor_id = spec["actor_id"]
        name, ns = spec.get("name"), spec.get("namespace", "default")
        if name is not None:
            existing = self.named_actors.get((ns, name))
            if existing is not None:
                if spec.get("get_if_exists"):
                    return {"actor_id": existing, "existing": True,
                            "info": self.actors[existing].info(),
                            "method_meta": self.actors[existing].spec.get("method_meta")}
                return {"error": f"actor name {name!r} taken in namespace {ns!r}"}
        entry = _ActorEntry(actor_id, spec)
        self.actors[actor_id] = entry
        if name is not None:
            self.named_actors[(ns, name)] = actor_id
        asyncio.ensure_future(self._schedule_actor(entry))
        return {"actor_id": actor_id, "existing": False}

    async def _schedule_actor(self, entry: _ActorEntry,
                              backoff: float = 0.0) -> None:
        if backoff:
            await asyncio.sleep(backoff)
        req = ResourceSet(entry.spec.get("resources", {}))
        strategy = entry.spec.get("scheduling_strategy")
        deadline = time.monotonic() + 3600.0
        while time.monotonic() < deadline:
            if entry.state == ACTOR_DEAD:
                return  # killed while pending/restarting
            views = {nid: n.view for nid, n in self.nodes.items() if n.alive}
            node_id = pick_node(strategy, views, req)
            if node_id is None:
                await asyncio.sleep(0.2)  # infeasible now; wait for nodes
                continue
            node = self.nodes[node_id]
            try:
                client = await self._pool.get(node.address)
                reply = await client.call("create_actor", {
                    "actor_id": entry.actor_id, "spec": entry.spec})
                if entry.state == ACTOR_DEAD:
                    # Killed during creation: reap the just-created worker.
                    if reply.get("ok"):
                        await client.call("kill_actor",
                                          {"actor_id": entry.actor_id})
                    return
                if reply.get("ok"):
                    entry.node_id = node_id
                    return  # raylet reports actor_update(ALIVE) when ready
                if reply.get("retry"):
                    await asyncio.sleep(0.2)
                    continue
                await self._finalize_actor_death(
                    entry, reply.get("error", "creation failed"))
                return
            except Exception as e:  # node unreachable — try another
                self._pool.invalidate(node.address)
                await asyncio.sleep(0.2)
        await self._finalize_actor_death(entry, "scheduling timed out")

    async def rpc_actor_update(self, p):
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"ok": False}
        state = p["state"]
        if state == ACTOR_ALIVE:
            if entry.state == ACTOR_DEAD:
                # Killed while the raylet was creating it — don't resurrect;
                # tell the raylet to reap the worker.
                node = self.nodes.get(p.get("node_id", ""))
                if node is not None:
                    try:
                        client = await self._pool.get(node.address)
                        await client.call("kill_actor",
                                          {"actor_id": entry.actor_id})
                    except Exception:
                        pass
                return {"ok": True}
            entry.state = ACTOR_ALIVE
            entry.address = p.get("address")
            entry.node_id = p.get("node_id", entry.node_id)
            self._wake_actor_waiters(entry)
        elif state == ACTOR_DEAD:
            await self._handle_actor_failure(entry, p.get("reason", "worker died"))
        return {"ok": True}

    async def _handle_actor_failure(self, entry: _ActorEntry, reason: str) -> None:
        if entry.state == ACTOR_DEAD:
            return
        max_restarts = entry.spec.get("max_restarts", 0)
        if entry.spec.get("_explicit_kill"):
            max_restarts = 0
        if max_restarts == -1 or entry.num_restarts < max_restarts:
            entry.num_restarts += 1
            entry.state = ACTOR_RESTARTING
            entry.address = None
            # Backoff happens inside the spawned task — this path runs on the
            # monitor loop and must not stall node-death handling.
            asyncio.ensure_future(self._schedule_actor(
                entry, backoff=get_config().actor_restart_backoff_s))
        else:
            await self._finalize_actor_death(entry, reason)

    async def _finalize_actor_death(self, entry: _ActorEntry, reason: str) -> None:
        entry.state = ACTOR_DEAD
        entry.death_reason = reason
        name, ns = entry.spec.get("name"), entry.spec.get("namespace", "default")
        if name is not None and self.named_actors.get((ns, name)) == entry.actor_id:
            del self.named_actors[(ns, name)]
        self._wake_actor_waiters(entry)

    def _wake_actor_waiters(self, entry: _ActorEntry) -> None:
        for fut in entry.waiters:
            if not fut.done():
                fut.set_result(True)
        entry.waiters.clear()

    async def rpc_get_actor_info(self, p):
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"error": "unknown actor"}
        if p.get("wait_alive"):
            deadline = time.monotonic() + p.get("timeout", 60.0)
            while entry.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                fut = asyncio.get_running_loop().create_future()
                entry.waiters.append(fut)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(fut, remaining)
                except asyncio.TimeoutError:
                    break
        return {"info": entry.info(),
                "method_meta": entry.spec.get("method_meta")}

    async def rpc_get_named_actor(self, p):
        actor_id = self.named_actors.get((p.get("namespace", "default"), p["name"]))
        if actor_id is None:
            return {"error": f"no actor named {p['name']!r}"}
        entry = self.actors[actor_id]
        return {"actor_id": actor_id, "info": entry.info(),
                "method_meta": entry.spec.get("method_meta")}

    async def rpc_kill_actor(self, p):
        entry = self.actors.get(p["actor_id"])
        if entry is None:
            return {"ok": False}
        entry.spec["_explicit_kill"] = True
        if entry.address and entry.node_id:
            node = self.nodes.get(entry.node_id)
            if node:
                try:
                    client = await self._pool.get(node.address)
                    await client.call("kill_actor", {"actor_id": entry.actor_id})
                except Exception:
                    pass
        await self._finalize_actor_death(entry, "killed via kill()")
        return {"ok": True}

    async def rpc_list_actors(self, p):
        return [a.info() for a in self.actors.values()]

    # ---- task routing (spillback target selection) -------------------------
    async def rpc_route_task(self, p):
        req = ResourceSet(p["resources"])
        views = {nid: n.view for nid, n in self.nodes.items() if n.alive}
        node_id = pick_node(p.get("strategy"), views, req,
                            preferred=p.get("preferred"))
        if node_id is None:
            return {"error": "infeasible", "node_id": None}
        return {"node_id": node_id, "address": self.nodes[node_id].address}

    # ---- cluster info -------------------------------------------------------
    async def rpc_cluster_resources(self, p):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.view.total.to_dict().items():
                total[k] = total.get(k, 0) + v
            for k, v in n.view.available.to_dict().items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def rpc_next_job_id(self, p):
        self._job_counter += 1
        return {"job_index": self._job_counter}
