"""Standalone node daemon: the process behind ``rt start``.

Reference analog: ``python/ray/_private/node.py`` (``start_head_processes
:1395``, ``start_ray_processes :1424``) — except the reference spawns
gcs_server/raylet as separate OS processes; here one daemon process hosts the
GCS (head only) and the raylet on a single asyncio loop. A worker-host daemon
(``--address``) joins an existing GCS over TCP, learning the session name
from the GCS KV — the path a second TPU-VM host takes to join the cluster.

State files land in ``<session_dir_root>/nodes/<node_id>.json`` (plus
``session_latest.json`` for the head) so ``rt status`` / ``rt stop`` /
``init(address="auto")`` can find the cluster without arguments.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import uuid
from typing import Dict, Optional

from ray_tpu._private import accelerator
from ray_tpu._private.config import get_config
from ray_tpu.core import resources as res


def state_dir() -> str:
    return os.path.join(get_config().session_dir_root, "nodes")


def session_latest_path() -> str:
    return os.path.join(get_config().session_dir_root, "session_latest.json")


def read_session_latest() -> Optional[Dict]:
    try:
        with open(session_latest_path()) as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return None


def node_resources(num_cpus: Optional[float], num_tpus: Optional[float],
                   extra: Optional[Dict[str, float]]) -> Dict[str, float]:
    total = {
        res.CPU: num_cpus if num_cpus is not None else (os.cpu_count() or 1),
        res.TPU: num_tpus if num_tpus is not None
        else accelerator.autodetect_num_tpu_chips(),
        res.MEMORY: float(os.sysconf("SC_PAGE_SIZE")
                          * os.sysconf("SC_PHYS_PAGES")),
    }
    total.update(extra or {})
    return {k: v for k, v in total.items() if v}


async def _amain(args: argparse.Namespace) -> None:
    from ray_tpu.cluster.gcs import GcsServer
    from ray_tpu.cluster.raylet import Raylet
    from ray_tpu.cluster.rpc import RpcClient, RpcServer

    # Marks this process as a standalone node daemon: destructive chaos
    # sites (gcs.kill) are only allowed to os._exit here, never inside a
    # driver-hosted in-process control plane (util/chaos.py).
    os.environ["RT_NODE_DAEMON"] = "1"

    loop = asyncio.get_running_loop()
    stop_ev = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)

    # Bind interface for EVERY server this node tree runs (GCS, raylet,
    # workers via RT_CONFIG_JSON): rt start --host 0.0.0.0 makes them all
    # reachable cross-host, advertising the outbound IP.
    if args.host and args.host != "127.0.0.1":
        get_config().bind_host = args.host

    gcs = gcs_server = None
    session_name = args.session_name
    gcs_address = args.address
    if args.head:
        session_name = session_name or f"session_{uuid.uuid4().hex[:12]}"
        # Same --session-name across head restarts => same snapshot file:
        # actors/PGs/KV survive the restart (GCS fault tolerance).
        persist = os.path.join(get_config().session_dir_root, session_name,
                               "gcs_snapshot.pkl")
        gcs = GcsServer(persist_path=persist)
        gcs_server = RpcServer(loop)
        gcs_server.register_object(gcs)
        await gcs_server.start(args.port)
        gcs.start_monitor()
        gcs_address = gcs_server.address
        gcs.kv["@session/name"] = session_name.encode()
    else:
        client = RpcClient(gcs_address, peer_id="node-join")
        await client.connect()
        reply = await client.call("kv_get", {"key": "@session/name"})
        val = reply.get("value")
        session_name = session_name or (
            val.decode() if isinstance(val, bytes) else val) or "session_shared"
        await client.close()

    node_id = uuid.uuid4().hex
    labels = dict(accelerator.tpu_node_labels())
    if args.labels:
        labels.update(json.loads(args.labels))
    labels["session"] = session_name
    if args.head:
        labels["node_role"] = "head"
    resources = node_resources(args.num_cpus, args.num_tpus,
                               json.loads(args.resources)
                               if args.resources else None)
    raylet = Raylet(node_id, session_name, gcs_address, resources, labels,
                    loop)
    await raylet.start()

    state = {
        "pid": os.getpid(), "node_id": node_id, "head": bool(args.head),
        "gcs_address": gcs_address,
        "raylet_address": raylet.server.address,
        "session_name": session_name,
        "resources": resources,
    }
    os.makedirs(state_dir(), exist_ok=True)
    state_path = os.path.join(state_dir(), f"{node_id}.json")
    # rt: lint-allow(event-loop-blocking) one-shot boot bookkeeping: two
    # tiny local writes before the daemon starts serving anything
    with open(state_path, "w") as f:
        json.dump(state, f)
    if args.head:
        # rt: lint-allow(event-loop-blocking) same boot-time write
        with open(session_latest_path(), "w") as f:
            json.dump(state, f)
    # The launching `rt start` blocks on this line.
    print("RT_NODE_READY " + json.dumps(state), flush=True)

    await stop_ev.wait()
    try:
        await raylet.stop()
        if gcs is not None:
            await gcs.stop()
            await gcs_server.stop()
    finally:
        try:
            os.unlink(state_path)
        except FileNotFoundError:
            pass
        if args.head:
            try:
                os.unlink(session_latest_path())
            except FileNotFoundError:
                pass


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="rt-node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None,
                   help="GCS address of an existing head to join")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind host for the head GCS (0.0.0.0 for multi-host)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default=None, help="JSON dict of extras")
    p.add_argument("--labels", default=None,
                   help="JSON dict of node labels (e.g. tpu-slice-name from "
                        "a pod-slice provider) merged over autodetected ones")
    p.add_argument("--session-name", default=None)
    args = p.parse_args(argv)
    if not args.head and not args.address:
        p.error("pass --head or --address=<gcs>")
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
