"""Per-node shared-memory object store (the plasma equivalent).

Reference analog: ``src/ray/object_manager/plasma/`` — a per-node store of
immutable sealed objects that every process on the node maps read-only with
zero copies. Redesign: instead of a store daemon owning one dlmalloc arena
with fd-passing (``fling.cc``), each object is a file in a tmpfs session
directory (``/dev/shm/rt_<session>/``): creators write+seal, readers mmap
read-only. The kernel page cache IS the shared arena; the raylet tracks
metadata/usage and performs eviction+spilling. This removes the single-daemon
allocation bottleneck and keeps crash cleanup trivial (rm -rf of the session
dir), at the cost of per-object mmap granularity — the right trade for ML
workloads with few large tensors.

Buffers returned by ``read`` point directly into the mapping; deserialized
numpy/jax host arrays alias that memory (pickle-5 zero-copy path).
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID

SHM_ROOT = "/dev/shm"


class PlasmaStore:
    """Create/seal/read/delete objects in the node's shm session dir."""

    def __init__(self, session_name: str, create_dir: bool = True):
        self.dir = os.path.join(SHM_ROOT, session_name)
        if create_dir:
            os.makedirs(self.dir, exist_ok=True)
        self._maps: Dict[ObjectID, Tuple[mmap.mmap, memoryview]] = {}
        self._lock = threading.Lock()

    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self.dir, oid.hex())

    def _tmp_path(self, oid: ObjectID) -> str:
        return self._path(oid) + ".building"

    def create(self, oid: ObjectID, size: int) -> memoryview:
        """A writable buffer; call ``seal`` when filled."""
        path = self._tmp_path(oid)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
            mm = mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)
        with self._lock:
            self._maps[oid] = (mm, memoryview(mm)[:size])
        return memoryview(mm)[:size]

    def seal(self, oid: ObjectID) -> int:
        """Atomically publish the object; returns its size."""
        os.rename(self._tmp_path(oid), self._path(oid))
        with self._lock:
            entry = self._maps.get(oid)
        return len(entry[1]) if entry else os.path.getsize(self._path(oid))

    def contains(self, oid: ObjectID) -> bool:
        return os.path.exists(self._path(oid))

    def read(self, oid: ObjectID) -> Optional[memoryview]:
        """Zero-copy read-only view, or None if absent."""
        with self._lock:
            entry = self._maps.get(oid)
            if entry is not None:
                return entry[1]
        path = self._path(oid)
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        view = memoryview(mm)
        with self._lock:
            self._maps[oid] = (mm, view)
        return view

    def write_whole(self, oid: ObjectID, payload: bytes) -> int:
        buf = self.create(oid, len(payload))
        buf[:] = payload
        return self.seal(oid)

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            entry = self._maps.pop(oid, None)
        if entry is not None:
            try:
                entry[1].release()
                entry[0].close()
            except BufferError:
                pass  # readers still hold views; file unlink below still works
        for path in (self._path(oid), self._tmp_path(oid)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def used_bytes(self) -> int:
        total = 0
        try:
            for name in os.listdir(self.dir):
                try:
                    total += os.path.getsize(os.path.join(self.dir, name))
                except FileNotFoundError:
                    pass
        except FileNotFoundError:
            pass
        return total

    def list_objects(self) -> List[ObjectID]:
        out = []
        try:
            for name in os.listdir(self.dir):
                if not name.endswith(".building"):
                    try:
                        out.append(ObjectID.from_hex(name))
                    except ValueError:
                        pass
        except FileNotFoundError:
            pass
        return out

    def destroy(self) -> None:
        with self._lock:
            for mm, view in self._maps.values():
                try:
                    view.release()
                    mm.close()
                except BufferError:
                    pass
            self._maps.clear()
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)
