"""GKE node provider: provision TPU pod slices as Kubernetes Pods.

Reference analog: ``python/ray/autoscaler/_private/kuberay/node_provider.py:1``
(KuberayNodeProvider — scales worker pods through the k8s API server, reads
pod state with label selectors, auths via the in-cluster serviceaccount).
Redesigned rather than ported:

  - **Direct Pod create/delete, no operator.** KubeRay patches a RayCluster
    CR and waits for the operator to reconcile; here the provider IS the
    reconciler — it creates/deletes Pods against the core v1 API directly,
    which removes the CR round-trip and the ``workersToDelete`` race the
    reference must guard (``safe_to_scale``).
  - **One provider node == one pod slice** (same atom as the TPU-VM
    provider, ``autoscaler/gcp.py``): a multi-host slice materializes as
    ``num_hosts`` Pods sharing a slice-name label, all pinned to the same
    GKE TPU nodepool via the ``cloud.google.com/gke-tpu-accelerator`` /
    ``gke-tpu-topology`` nodeSelectors; a terminate deletes the whole group.
  - **Slice labels flow from GKE metadata.** Each pod carries the
    GKE-injected TPU env (``TPU_WORKER_ID``/``TPU_TOPOLOGY``/…); node_main
    maps them to framework slice labels via
    ``_private/accelerator.py:gke_node_labels`` (the reference's
    RAY_GCE_TPU_ACCELERATOR_ENDPOINT analog, ``ray_constants.py:488-494``).
  - The HTTP transport + serviceaccount credentials are injectable: tests
    run the full provider against ``FakeK8sHttp`` which "schedules" pods as
    real local ``node_main`` daemons (fake the cloud, keep the runtime
    real); production uses urllib against ``kubernetes.default.svc`` with
    the mounted token.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.core.resources import (
    LABEL_SLICE_NAME,
    LABEL_SLICE_TOPOLOGY,
)

# In-cluster defaults (the mounted serviceaccount, like the reference's
# load_k8s_secrets at kuberay/node_provider.py:135).
SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
K8S_API_BASE = "https://kubernetes.default.svc"

# Pod labels this provider owns (KubeRay analog: ray.io/node-type,
# ray.io/group — kuberay/node_provider.py:28-31).
LABEL_CLUSTER = "rt.io/cluster"
LABEL_NODE_TYPE = "rt.io/node-type"
LABEL_SLICE = "rt.io/slice"

# GKE TPU nodepool selectors (how GKE routes pods onto TPU node pools).
GKE_SEL_ACCEL = "cloud.google.com/gke-tpu-accelerator"
GKE_SEL_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

# http transport signature: (method, url, headers, body_json_or_None)
#   -> (status_code, response_dict)
HttpFn = Callable[[str, str, Dict[str, str], Optional[Dict]],
                  Tuple[int, Dict]]


def _urllib_http(method: str, url: str, headers: Dict[str, str],
                 body: Optional[Dict]) -> Tuple[int, Dict]:
    import ssl
    import urllib.error
    import urllib.request

    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={**headers,
                                          "Content-Type": "application/json"})
    ctx = ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
    try:
        with urllib.request.urlopen(req, timeout=30, context=ctx) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload


def _sa_token() -> str:
    with open(f"{SA_DIR}/token") as f:
        return f.read().strip()


def _sa_namespace() -> str:
    with open(f"{SA_DIR}/namespace") as f:
        return f.read().strip()


class K8sClient:
    """Thin typed wrapper over the core/v1 Pods collection."""

    def __init__(self, namespace: Optional[str] = None,
                 http: Optional[HttpFn] = None,
                 token_provider: Optional[Callable[[], str]] = None,
                 base_url: str = K8S_API_BASE):
        self.namespace = namespace or _sa_namespace()
        self._http = http or _urllib_http
        self._token = token_provider or _sa_token
        self._base = base_url

    def _call(self, method: str, path: str,
              body: Optional[Dict] = None) -> Dict:
        headers = {"Authorization": f"Bearer {self._token()}"}
        status, payload = self._http(method, f"{self._base}{path}", headers,
                                     body)
        if status >= 400:
            raise RuntimeError(
                f"k8s API {method} {path} failed: HTTP {status} "
                f"{payload.get('message', payload)}")
        return payload

    def create_pod(self, pod: Dict) -> Dict:
        return self._call(
            "POST", f"/api/v1/namespaces/{self.namespace}/pods", pod)

    def delete_pod(self, name: str) -> Dict:
        return self._call(
            "DELETE", f"/api/v1/namespaces/{self.namespace}/pods/{name}")

    def list_pods(self, label_selector: str = "") -> List[Dict]:
        sel = f"?labelSelector={label_selector}" if label_selector else ""
        payload = self._call(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods{sel}")
        return payload.get("items", [])


class GkeTpuPodProvider(NodeProvider):
    """Autoscaler NodeProvider provisioning TPU slices as GKE Pod groups.

    ``node_types`` spec per type::

        {"v5e_2x4": {"accelerator": "tpu-v5-lite-podslice",  # GKE selector
                     "accelerator_type": "v5litepod-8",  # webhook format
                     "topology": "2x4",
                     "num_hosts": 2, "chips_per_host": 4,
                     "image": "gcr.io/…/rt:latest",
                     "cpu": "4", "memory": "16Gi",           # per-host pod
                     "resources": {"CPU": 8, "TPU": 8}}}     # SLICE aggregate

    One provider node is one slice: ``create_node`` creates ``num_hosts``
    Pods sharing an ``rt.io/slice`` label; ``terminate_node`` deletes the
    group; ``non_terminated_nodes`` groups live pods by that label.
    """

    def __init__(self, gcs_address: str, node_types: Dict[str, Dict],
                 cluster_name: str = "rt",
                 k8s: Optional[K8sClient] = None):
        self.gcs_address = gcs_address
        self.cluster_name = cluster_name
        self.node_types = dict(node_types)
        self.k8s = k8s or K8sClient()

    # -- pod template ---------------------------------------------------------
    def _pod_body(self, slice_name: str, node_type: str, worker_id: int,
                  spec: Dict) -> Dict:
        chips = int(spec.get("chips_per_host", 4))
        num_hosts = int(spec.get("num_hosts", 1))
        # GKE injects TPU_WORKER_ID etc. via its TPU webhook on real
        # clusters; setting them explicitly keeps the contract when the
        # webhook is absent (and in the fake). node_main maps them to
        # slice labels (accelerator.py:gke_node_labels).
        env = [
            {"name": "TPU_NAME", "value": slice_name},
            {"name": "TPU_WORKER_ID", "value": str(worker_id)},
            {"name": "TPU_TOPOLOGY", "value": spec.get("topology", "")},
            {"name": "RT_NUM_TPUS", "value": str(chips)},
        ]
        # TPU_ACCELERATOR_TYPE carries the webhook format ("v5litepod-16"),
        # NOT the nodeSelector string ("tpu-v5-lite-podslice") — mixing
        # them would make gke_node_labels derive a bogus accelerator-type
        # label. Only set when the spec supplies the webhook form.
        if spec.get("accelerator_type"):
            env.append({"name": "TPU_ACCELERATOR_TYPE",
                        "value": spec["accelerator_type"]})
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{slice_name}-{worker_id}",
                "labels": {LABEL_CLUSTER: self.cluster_name,
                           LABEL_NODE_TYPE: node_type,
                           LABEL_SLICE: slice_name},
            },
            "spec": {
                "restartPolicy": "Never",
                "nodeSelector": {
                    GKE_SEL_ACCEL: spec.get("accelerator", ""),
                    GKE_SEL_TOPOLOGY: spec.get("topology", ""),
                },
                "containers": [{
                    "name": "rt-worker",
                    "image": spec.get("image", "rt:latest"),
                    "command": ["python", "-m", "ray_tpu.cluster.node_main",
                                "--address", self.gcs_address],
                    "env": env,
                    "resources": {
                        "requests": {"cpu": spec.get("cpu", "1"),
                                     "memory": spec.get("memory", "4Gi"),
                                     "google.com/tpu": str(chips)},
                        "limits": {"google.com/tpu": str(chips)},
                    },
                }],
            },
        }

    # -- NodeProvider ---------------------------------------------------------
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        spec = self.node_types[node_type]
        slice_name = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:6]}"
        created = []
        try:
            for worker_id in range(int(spec.get("num_hosts", 1))):
                body = self._pod_body(slice_name, node_type, worker_id, spec)
                self.k8s.create_pod(body)
                created.append(body["metadata"]["name"])
        except Exception:
            # partial slice is useless — roll back already-created pods
            for name in created:
                try:
                    self.k8s.delete_pod(name)
                except Exception:  # noqa: BLE001
                    pass
            raise
        return slice_name

    def terminate_node(self, provider_node_id: str) -> None:
        pods = self.k8s.list_pods(
            label_selector=f"{LABEL_SLICE}={provider_node_id}")
        for pod in pods:
            try:
                self.k8s.delete_pod(pod["metadata"]["name"])
            except Exception:  # noqa: BLE001 — best-effort group delete
                pass

    def non_terminated_nodes(self) -> List[Dict]:
        pods = self.k8s.list_pods(
            label_selector=f"{LABEL_CLUSTER}={self.cluster_name}")
        slices: Dict[str, Dict] = {}
        for pod in pods:
            phase = pod.get("status", {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            meta = pod["metadata"]
            slice_name = meta["labels"].get(LABEL_SLICE, meta["name"])
            node_type = meta["labels"].get(LABEL_NODE_TYPE, "")
            entry = slices.setdefault(slice_name, {
                "provider_node_id": slice_name,
                "node_type": node_type,
                "labels": {LABEL_SLICE_NAME: slice_name,
                           LABEL_SLICE_TOPOLOGY: pod["spec"]
                           .get("nodeSelector", {})
                           .get(GKE_SEL_TOPOLOGY, ""),
                           **meta["labels"]},
                "created_at": meta.get("creationTimestamp", 0) or 0,
                "num_hosts": 0,
            })
            entry["num_hosts"] += 1
        # a slice whose pods are mid-create still counts whole: the
        # spec's num_hosts wins over observed pods (same booting/slot
        # rationale as the TPU-VM provider, gcp.py)
        for entry in slices.values():
            spec_hosts = self.node_types.get(
                entry["node_type"], {}).get("num_hosts")
            if spec_hosts:
                entry["num_hosts"] = int(spec_hosts)
        return list(slices.values())


class FakeK8sHttp:
    """In-memory k8s core/v1 API double that BOOTS real local nodes.

    Reference analog: the fake-multinode provider pattern
    (``autoscaler/_private/fake_multi_node/node_provider.py``) — fake the
    API server, keep everything below real. A pod create "schedules" one
    ``node_main`` daemon with the pod's TPU env (so GKE label mapping is
    exercised end to end); a delete terminates it.
    """

    def __init__(self, gcs_address: str, cpus_per_host: float = 1,
                 boot: bool = True):
        self.gcs_address = gcs_address
        self.cpus_per_host = cpus_per_host
        self.boot = boot
        self.pods: Dict[str, Dict] = {}
        self._procs: Dict[str, object] = {}
        self.requests: List[Tuple[str, str]] = []

    def __call__(self, method: str, url: str, headers: Dict[str, str],
                 body: Optional[Dict]) -> Tuple[int, Dict]:
        assert headers.get("Authorization", "").startswith("Bearer "), \
            "request without serviceaccount token"
        path = url.split("/api/v1/", 1)[1]
        self.requests.append((method, path))
        if method == "POST" and path.endswith("/pods"):
            return self._create(body)
        if method == "DELETE":
            return self._delete(path.rsplit("/", 1)[-1])
        if method == "GET" and "/pods" in path:
            selector = ""
            if "labelSelector=" in path:
                selector = path.split("labelSelector=", 1)[1]
            return 200, {"items": self._select(selector)}
        return 400, {"message": f"unhandled {method} {path}"}

    def _select(self, selector: str) -> List[Dict]:
        items = []
        want = {}
        if selector:
            for kv in selector.split(","):
                k, _, v = kv.partition("=")
                want[k] = v
        for pod in self.pods.values():
            labels = pod["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in want.items()):
                items.append(dict(pod))
        return items

    def _create(self, body: Dict) -> Tuple[int, Dict]:
        name = body["metadata"]["name"]
        if name in self.pods:
            return 409, {"message": "already exists"}
        sel = body["spec"].get("nodeSelector", {})
        if not sel.get(GKE_SEL_ACCEL) or not sel.get(GKE_SEL_TOPOLOGY):
            return 400, {"message": "TPU pod missing gke-tpu nodeSelectors"}
        tpu_req = body["spec"]["containers"][0]["resources"][
            "requests"].get("google.com/tpu")
        if not tpu_req:
            return 400, {"message": "pod does not request google.com/tpu"}
        pod = dict(body)
        pod["status"] = {"phase": "Running", "podIP": "10.0.0.1"}
        self.pods[name] = pod
        if self.boot:
            self._boot_host(name, body)
        return 201, dict(pod)

    def _delete(self, name: str) -> Tuple[int, Dict]:
        if name not in self.pods:
            return 404, {"message": "not found"}
        proc = self._procs.pop(name, None)
        if proc is not None:
            proc.terminate()
        self.pods.pop(name)
        return 200, {}

    def _boot_host(self, name: str, body: Dict) -> None:
        import os
        import subprocess
        import sys

        import ray_tpu

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = ":".join(
            [repo_root] + [p for p in env.get("PYTHONPATH", "").split(":")
                           if p])
        # the pod's TPU env IS the label source (gke_node_labels)
        for item in body["spec"]["containers"][0].get("env", []):
            env[item["name"]] = item["value"]
        chips = env.get("RT_NUM_TPUS", "0")
        args = [sys.executable, "-m", "ray_tpu.cluster.node_main",
                "--address", self.gcs_address,
                "--num-cpus", str(self.cpus_per_host),
                "--num-tpus", chips]
        self._procs[name] = subprocess.Popen(
            args, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, start_new_session=True)

    def shutdown(self) -> None:
        for name in list(self.pods):
            self._delete(name)
