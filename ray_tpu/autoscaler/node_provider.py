"""NodeProvider plugin API + the local subprocess provider.

Reference analog: ``python/ray/autoscaler/node_provider.py:13`` (the plugin
interface cloud integrations implement) and
``autoscaler/_private/fake_multi_node/node_provider.py:237`` (the test
provider). ``LocalNodeProvider`` improves on the fake: nodes are REAL
``node_main`` daemons joining the GCS over TCP, so scheduling, object
transfer, and failure paths are exercised, not simulated. A GCP/TPU-pod
provider implements the same three methods with cloud calls.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal surface the autoscaler needs (create/list/terminate)."""

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Dict]:
        """[{provider_node_id, node_type, labels, created_at}]"""
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch worker-node daemons on this machine (one process per node)."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._nodes: Dict[str, Dict] = {}

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        pid_label = f"as-{node_type}-{uuid.uuid4().hex[:6]}"
        args = [sys.executable, "-m", "ray_tpu.cluster.node_main",
                "--address", self.gcs_address]
        res = dict(resources)
        num_cpus = res.pop("CPU", None)
        num_tpus = res.pop("TPU", None)
        if num_cpus is not None:
            args += ["--num-cpus", str(num_cpus)]
        if num_tpus is not None:
            args += ["--num-tpus", str(num_tpus)]
        if res:
            args += ["--resources", json.dumps(res)]
        if labels:
            # the node must register with these labels so cluster-side
            # consumers (v2 instance binding, label scheduling) see them
            args += ["--labels", json.dumps(labels)]
        proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL,
                                start_new_session=True)
        # wait for the ready line so the GCS knows the node before we return
        node_id = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = proc.stdout.readline().decode()
            if not line:
                break
            if line.startswith("RT_NODE_READY "):
                node_id = json.loads(line[len("RT_NODE_READY "):])["node_id"]
                break
        if node_id is None:
            proc.terminate()
            raise RuntimeError(f"node of type {node_type!r} failed to start")
        self._nodes[pid_label] = {
            "provider_node_id": pid_label, "node_type": node_type,
            "labels": dict(labels), "created_at": time.time(),
            "pid": proc.pid, "gcs_node_id": node_id,
        }
        return pid_label

    def terminate_node(self, provider_node_id: str) -> None:
        info = self._nodes.pop(provider_node_id, None)
        if info is None:
            return
        try:
            os.kill(info["pid"], signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    def non_terminated_nodes(self) -> List[Dict]:
        alive = []
        for pid_label, info in list(self._nodes.items()):
            try:
                os.kill(info["pid"], 0)
                alive.append(dict(info))
            except (ProcessLookupError, PermissionError):
                self._nodes.pop(pid_label, None)
        return alive
