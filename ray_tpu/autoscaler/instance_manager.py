"""Autoscaler v2: the instance-state-machine architecture.

Reference analog: ``python/ray/autoscaler/v2/instance_manager/`` —
``InstanceStorage`` (``instance_storage.py:31``: versioned store with
status-change subscribers) plus the reconciler that drives every cloud
instance through an explicit lifecycle instead of v1's stateless
diff-and-launch loop. The v2 design's point: every transition is recorded
and observable, and stuck states (a node that never joined the cluster, a
launch loop against an out-of-quota provider) are detected by timeouts
and circuit breakers rather than inferred. The store here is in-memory
(REQUESTED is transient because ``create_node`` is synchronous); a
durable store slots in behind the same surface.

Lifecycle::

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPING
         \\         \\            \\                         -> TERMINATED
          \\         \\            -> (join timeout) TERMINATED
           \\         -> ALLOCATION_FAILED (retry or give up)
            -> ...

``InstanceManager.reconcile()`` is the single idempotent step: it compares
target counts against live instances, launches/terminates through the
same :class:`NodeProvider` plugin surface v1 uses, matches provider nodes
to GCS cluster membership to detect RAY_RUNNING, and expires stuck
states. The v1 ``StandardAutoscaler`` stays the demand brain; this is the
execution substrate under explicit scale targets (``rt up``-style
declarative configs, tests, or the demand loop).
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

# lifecycle states
QUEUED = "QUEUED"                       # decided, not yet requested
REQUESTED = "REQUESTED"                 # provider.create_node in flight
ALLOCATED = "ALLOCATED"                 # provider says it exists
RAY_RUNNING = "RAY_RUNNING"             # joined the GCS (serving)
RAY_STOPPING = "RAY_STOPPING"           # drain requested
TERMINATED = "TERMINATED"               # gone (terminal)
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # create failed (terminal, counted)

_LIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    provider_node_id: Optional[str] = None
    gcs_node_id: Optional[str] = None
    error: Optional[str] = None
    launch_attempts: int = 0
    version: int = 0
    # status -> wall time of entry (the audit trail the v2 design exists
    # to provide)
    status_history: List[Tuple[str, float]] = dataclasses.field(
        default_factory=list)

    def at(self, status: str) -> Optional[float]:
        for s, t in reversed(self.status_history):
            if s == status:
                return t
        return None


class InstanceStorage:
    """Versioned instance table with optimistic concurrency + subscribers
    (reference: ``instance_storage.py:31``). Single-process here — the
    version check guards interleaved reconciler/operator updates, and
    subscribers feed observability (event log, metrics)."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}
        self._version = 0
        self._subscribers: List[Callable[[Instance, str], None]] = []

    @property
    def version(self) -> int:
        return self._version

    def subscribe(self, fn: Callable[[Instance, str], None]) -> None:
        """fn(instance, old_status) after every status change."""
        self._subscribers.append(fn)

    def upsert(self, inst: Instance,
               expected_version: Optional[int] = None) -> Tuple[bool, int]:
        """CAS upsert: fails (False, current_global_version) when the
        caller's snapshot of THIS instance is stale (its stored record's
        version moved since the snapshot was taken)."""
        old = self._instances.get(inst.instance_id)
        if expected_version is not None and \
                (old.version if old else 0) != expected_version:
            # per-INSTANCE CAS: the caller's snapshot of this record is
            # stale (someone else transitioned it since); global-version
            # CAS would spuriously abort on unrelated instances' writes
            return False, self._version
        old_status = old.status if old else None
        stored = self._copy(inst)
        if old is not None:
            # the TABLE owns the audit trail: callers may hold stale
            # copies whose history misses intermediate transitions
            stored.status_history = list(old.status_history)
        if old_status != stored.status:
            stored.status_history.append((stored.status, time.time()))
        self._version += 1
        stored.version = inst.version = self._version
        # store a COPY: the table must not alias the caller's mutable
        # object, or later caller mutations silently bypass upsert (no
        # version bump, no subscriber event, broken CAS)
        self._instances[stored.instance_id] = stored
        if old_status != stored.status:
            for fn in self._subscribers:
                try:
                    # subscribers get a COPY too — a mutating observer
                    # must not edit the table behind the version counter
                    fn(self._copy(stored), old_status)
                except Exception:  # noqa: BLE001 — observers never break us
                    pass
        return True, self._version

    @staticmethod
    def _copy(inst: Instance) -> Instance:
        return dataclasses.replace(
            inst, resources=dict(inst.resources), labels=dict(inst.labels),
            status_history=list(inst.status_history))

    def get(self, instance_id: str) -> Optional[Instance]:
        inst = self._instances.get(instance_id)
        return self._copy(inst) if inst is not None else None

    def list(self, statuses: Optional[Tuple[str, ...]] = None
             ) -> List[Instance]:
        out = list(self._instances.values())
        if statuses is not None:
            out = [i for i in out if i.status in statuses]
        return [self._copy(i) for i in out]

    def delete(self, instance_id: str) -> None:
        if instance_id in self._instances:
            self._version += 1
            del self._instances[instance_id]


class InstanceManager:
    """Reconciler: drives instances toward per-type target counts.

    ``gcs_nodes_fn`` returns the live cluster membership
    (``[{node_id, alive, labels}]``) — how ALLOCATED instances are
    recognized as RAY_RUNNING and dead ones retired, mirroring the
    reference's cloud-instance <-> ray-node binding.
    """

    def __init__(self, provider: NodeProvider,
                 node_types: Dict[str, Dict],
                 gcs_nodes_fn: Callable[[], List[Dict]],
                 storage: Optional[InstanceStorage] = None,
                 max_launch_retries: int = 2,
                 join_timeout_s: float = 300.0,
                 failure_backoff_s: float = 10.0,
                 max_terminal_records: int = 256):
        self.storage = storage or InstanceStorage()
        self._provider = provider
        self._node_types = node_types  # name -> {resources, labels, ...}
        self._gcs_nodes_fn = gcs_nodes_fn
        self._targets: Dict[str, int] = {}
        self._max_retries = max_launch_retries
        self._join_timeout_s = join_timeout_s
        # per-node-type launch circuit breaker: each ALLOCATION_FAILED
        # doubles the pause before replacements queue again (capped), so
        # a provider that is permanently out of quota is probed at a
        # gentle rate instead of hammered every pass
        self._backoff_base_s = failure_backoff_s
        self._backoff_until: Dict[str, float] = {}
        self._backoff_mult: Dict[str, int] = {}
        self._max_terminal = max_terminal_records

    # ---- target surface ---------------------------------------------------
    def set_target(self, node_type: str, count: int) -> None:
        if node_type not in self._node_types:
            raise KeyError(f"unknown node type {node_type!r}; have "
                           f"{sorted(self._node_types)}")
        self._targets[node_type] = max(0, int(count))

    def targets(self) -> Dict[str, int]:
        return dict(self._targets)

    # ---- the reconcile step ----------------------------------------------
    def reconcile(self) -> Dict[str, int]:
        """One idempotent pass; returns a transition-count summary."""
        summary = {"launched": 0, "running": 0, "terminated": 0,
                   "failed": 0, "queued": 0}
        provider_nodes = {n["provider_node_id"]: n
                          for n in self._provider.non_terminated_nodes()}
        gcs_nodes = {n.get("labels", {}).get("as-instance-id"): n
                     for n in self._gcs_nodes_fn()}

        # 1. queue/trim toward targets
        self._fill_targets(summary, trim=True)

        # 2. drive state transitions. Each write is per-instance-CAS'd on
        # the snapshot version: an operator (or subscriber) transition that
        # interleaves wins, and this pass simply skips the record.
        for inst in self.storage.list():
            if inst.status == QUEUED:
                self._launch(inst, summary)
            elif inst.status == ALLOCATED:
                node = gcs_nodes.get(inst.instance_id)
                if node is not None and node.get("alive", True):
                    inst.gcs_node_id = node["node_id"]
                    inst.status = RAY_RUNNING
                    if self.storage.upsert(
                            inst, expected_version=inst.version)[0]:
                        summary["running"] += 1
                elif node is not None:
                    # joined and ALREADY died between passes — don't sit
                    # out the join timeout on a corpse
                    self._terminate(inst, "died before first observation",
                                    summary)
                elif inst.provider_node_id not in provider_nodes:
                    self._fail(inst, "provider node disappeared before "
                                     "joining", summary)
                elif time.time() - (inst.at(ALLOCATED) or 0) \
                        > self._join_timeout_s:
                    self._terminate(inst, "never joined the cluster",
                                    summary)
            elif inst.status == RAY_RUNNING:
                node = gcs_nodes.get(inst.instance_id)
                if inst.provider_node_id not in provider_nodes or (
                        node is not None and not node.get("alive", True)):
                    # died underneath us: record and (if still targeted)
                    # the next pass re-queues a replacement
                    self._terminate(inst, "node died", summary)
            elif inst.status == RAY_STOPPING:
                if inst.provider_node_id not in provider_nodes:
                    inst.status = TERMINATED
                    if self.storage.upsert(
                            inst, expected_version=inst.version)[0]:
                        summary["terminated"] += 1
                else:
                    self._provider.terminate_node(inst.provider_node_id)

        # 3. instances retired during this pass leave a shortfall —
        # queue replacements NOW so recovery doesn't wait a full period
        self._fill_targets(summary, trim=False)
        self._gc_terminal_records()
        return summary

    def _fill_targets(self, summary: Dict[str, int], trim: bool) -> None:
        now = time.time()
        by_type: Dict[str, List[Instance]] = {t: [] for t in self._targets}
        for i in self.storage.list(_LIVE_STATES):
            if i.node_type in by_type:
                by_type[i.node_type].append(i)
        for node_type, want in self._targets.items():
            live = by_type[node_type]
            if want > len(live) and \
                    now >= self._backoff_until.get(node_type, 0.0):
                for _ in range(want - len(live)):
                    inst = Instance(
                        instance_id=f"inst-{uuid.uuid4().hex[:8]}",
                        node_type=node_type,
                        resources=dict(self._node_types[node_type]
                                       .get("resources", {})),
                        labels=dict(self._node_types[node_type]
                                    .get("labels", {})))
                    self.storage.upsert(inst)
                    summary["queued"] += 1
            if trim and want < len(live):
                # retire surplus: never-joined first, then newest
                surplus = sorted(
                    live, key=lambda i: (i.status == RAY_RUNNING,
                                         -(i.at(i.status) or 0)))
                for inst in surplus[:len(live) - want]:
                    self._stop(inst, summary)

    def _gc_terminal_records(self) -> None:
        """Bound the terminal-record history (the audit trail is useful,
        unbounded growth across weeks of churn is not)."""
        terminal = self.storage.list((TERMINATED, ALLOCATION_FAILED))
        if len(terminal) <= self._max_terminal:
            return
        terminal.sort(key=lambda i: i.at(i.status) or 0)
        for inst in terminal[:len(terminal) - self._max_terminal]:
            self.storage.delete(inst.instance_id)

    # ---- transitions ------------------------------------------------------
    def _launch(self, inst: Instance, summary: Dict[str, int]) -> None:
        inst.status = REQUESTED
        inst.launch_attempts += 1
        self.storage.upsert(inst)
        try:
            nt = self._node_types[inst.node_type]
            labels = {**inst.labels, "as-instance-id": inst.instance_id}
            inst.provider_node_id = self._provider.create_node(
                inst.node_type, dict(nt.get("resources", {})), labels)
        except Exception as e:  # noqa: BLE001 — cloud errors are data
            if inst.launch_attempts <= self._max_retries:
                inst.status = QUEUED  # retry next pass
                inst.error = f"attempt {inst.launch_attempts}: {e}"
                self.storage.upsert(inst)
            else:
                self._fail(inst, str(e), summary)
            return
        inst.status = ALLOCATED
        self.storage.upsert(inst)
        summary["launched"] += 1
        self._backoff_mult.pop(inst.node_type, None)
        self._backoff_until.pop(inst.node_type, None)

    def _stop(self, inst: Instance, summary: Dict[str, int]) -> None:
        if inst.status in (QUEUED,):
            inst.status = TERMINATED
            self.storage.upsert(inst)
            summary["terminated"] += 1
            return
        inst.status = RAY_STOPPING
        self.storage.upsert(inst)
        if inst.provider_node_id:
            try:
                self._provider.terminate_node(inst.provider_node_id)
            except Exception:  # noqa: BLE001 — retried next pass
                pass

    def _terminate(self, inst: Instance, reason: str,
                   summary: Dict[str, int]) -> None:
        inst.error = reason
        if inst.provider_node_id:
            try:
                self._provider.terminate_node(inst.provider_node_id)
            except Exception:  # noqa: BLE001
                pass
        inst.status = TERMINATED
        self.storage.upsert(inst)
        summary["terminated"] += 1

    def _fail(self, inst: Instance, reason: str,
              summary: Dict[str, int]) -> None:
        inst.error = reason
        inst.status = ALLOCATION_FAILED
        self.storage.upsert(inst)
        summary["failed"] += 1
        # circuit-break this node type: exponential pause before the next
        # replacement attempt, reset by the first successful launch
        mult = self._backoff_mult.get(inst.node_type, 0)
        self._backoff_mult[inst.node_type] = min(mult + 1, 6)  # <= 64x
        self._backoff_until[inst.node_type] = time.time() + \
            self._backoff_base_s * (2 ** mult)
