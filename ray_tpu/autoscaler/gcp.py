"""GCP TPU-pod node provider: provision whole pod slices via the TPU VM API.

Reference analog: ``python/ray/autoscaler/_private/gcp/node_provider.py`` +
``node.py:GCPTPUNode`` (the v2 TPU REST surface: ``tpu.googleapis.com/v2
/projects/{p}/locations/{zone}/nodes``) and the pod YAMLs
(``autoscaler/gcp/tpu.yaml``, ``example-tpu-pod-topology.yaml``). Redesigned
TPU-first rather than ported:

  - **One provider node == one pod slice.** The reference treats each TPU VM
    host as a separate cloud node and leaves gang semantics to Ray; here the
    slice is the provisioning atom (a v5p-16 create yields all its hosts at
    once, and a terminate releases the whole slice), matching how the TPU API
    itself works and how ``slice_group()`` reserves capacity.
  - Every host boots ``node_main`` with topology labels
    (``tpu-slice-name``/``tpu-slice-topology``/``tpu-worker-id`` —
    ``core/resources.py:31``), so scheduler slice-affinity and
    ``mesh_for_slice_group`` work with zero extra plumbing.
  - The HTTP transport and auth-token source are injectable: tests run the
    full provider against ``FakeTpuRestHttp`` (which "boots" hosts as real
    local ``node_main`` daemons); production uses urllib + the GCE metadata
    token — this environment has zero egress, so the real transport is
    exercised only by its unit seam.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.core.resources import (
    LABEL_SLICE_NAME,
    LABEL_SLICE_TOPOLOGY,
)

TPU_API_BASE = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

# http transport signature: (method, url, headers, body_json_or_None)
#   -> (status_code, response_dict)
HttpFn = Callable[[str, str, Dict[str, str], Optional[Dict]],
                  Tuple[int, Dict]]


def _urllib_http(method: str, url: str, headers: Dict[str, str],
                 body: Optional[Dict]) -> Tuple[int, Dict]:
    import urllib.error
    import urllib.request

    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={**headers,
                                          "Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload


def _metadata_token() -> str:
    status, payload = _urllib_http(
        "GET", METADATA_TOKEN_URL, {"Metadata-Flavor": "Google"}, None)
    if status != 200:
        raise RuntimeError(f"metadata token fetch failed: HTTP {status}")
    return payload["access_token"]


class TpuRestClient:
    """Thin typed wrapper over the TPU VM v2 REST nodes collection."""

    def __init__(self, project: str, zone: str,
                 http: Optional[HttpFn] = None,
                 token_provider: Optional[Callable[[], str]] = None,
                 base_url: str = TPU_API_BASE):
        self.project = project
        self.zone = zone
        self._http = http or _urllib_http
        self._token = token_provider or _metadata_token
        self._base = base_url

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _call(self, method: str, path: str,
              body: Optional[Dict] = None) -> Dict:
        headers = {"Authorization": f"Bearer {self._token()}"}
        status, payload = self._http(method, f"{self._base}/{path}", headers,
                                     body)
        if status >= 400:
            raise RuntimeError(
                f"TPU API {method} {path} failed: HTTP {status} "
                f"{payload.get('error', payload)}")
        return payload

    def create_node(self, node_id: str, body: Dict) -> Dict:
        return self._call("POST", f"{self._parent}/nodes?nodeId={node_id}",
                          body)

    def delete_node(self, node_id: str) -> Dict:
        return self._call("DELETE", f"{self._parent}/nodes/{node_id}")

    def get_node(self, node_id: str) -> Dict:
        return self._call("GET", f"{self._parent}/nodes/{node_id}")

    def list_nodes(self) -> List[Dict]:
        nodes: List[Dict] = []
        token = ""
        while True:
            path = f"{self._parent}/nodes" + (
                f"?pageToken={token}" if token else "")
            payload = self._call("GET", path)
            nodes.extend(payload.get("nodes", []))
            token = payload.get("nextPageToken", "")
            if not token:
                return nodes


class GcpTpuPodProvider(NodeProvider):
    """Autoscaler NodeProvider provisioning TPU pod slices.

    ``node_types`` spec per type (the cluster-YAML essentials)::

        {"v5p_16": {"accelerator_type": "v5p-16",   # or topology+generation
                    "topology": "2x2x2",            # optional (XOR with type)
                    "runtime_version": "tpu-ubuntu2204-base",
                    "num_hosts": 2,                 # host VMs per slice
                    "resources": {"CPU": 2, "TPU": 8}}}   # SLICE aggregate

    ``resources`` is the slice-aggregate bag the autoscaler bin-packs
    against (StandardAutoscaler treats one provider node as one unit of
    capacity — for a pod slice that unit is the whole slice).
    """

    def __init__(self, gcs_address: str, project: str, zone: str,
                 node_types: Dict[str, Dict],
                 cluster_name: str = "rt",
                 rest: Optional[TpuRestClient] = None):
        self.gcs_address = gcs_address
        self.cluster_name = cluster_name
        self.node_types = dict(node_types)
        self.rest = rest or TpuRestClient(project, zone)

    # -- helpers --------------------------------------------------------------
    def _startup_script(self, slice_name: str, spec: Dict) -> str:
        labels = {LABEL_SLICE_NAME: slice_name,
                  LABEL_SLICE_TOPOLOGY: spec.get("topology", "")}
        # TPU_WORKER_ID is set by the TPU runtime on each host VM; chips and
        # generation are autodetected by node_main (accelerator.py).
        return (
            "#!/bin/bash\n"
            "# ray_tpu worker bring-up (assumes the image bakes the wheel)\n"
            f"python -m ray_tpu.cluster.node_main "
            f"--address {self.gcs_address} "
            f"--labels '{json.dumps(labels)}'\n")

    # -- NodeProvider ---------------------------------------------------------
    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        spec = self.node_types[node_type]
        slice_name = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:6]}"
        body = {
            "runtimeVersion": spec.get("runtime_version",
                                       "tpu-ubuntu2204-base"),
            "labels": {"rt-cluster": self.cluster_name,
                       "rt-node-type": node_type,
                       **{k.replace("/", "-"): v for k, v in labels.items()}},
            "metadata": {"startup-script":
                         self._startup_script(slice_name, spec)},
        }
        # The v2 API takes EXACTLY ONE of acceleratorType ("v5p-16") or
        # acceleratorConfig ({type, topology}) — sending both is a 400.
        if spec.get("topology"):
            body["acceleratorConfig"] = {
                "type": spec.get("chip_generation", "V5P"),
                "topology": spec["topology"]}
        else:
            body["acceleratorType"] = spec["accelerator_type"]
        self.rest.create_node(slice_name, body)
        return slice_name

    def terminate_node(self, provider_node_id: str) -> None:
        self.rest.delete_node(provider_node_id)

    def non_terminated_nodes(self) -> List[Dict]:
        out = []
        for node in self.rest.list_nodes():
            node_labels = node.get("labels", {})
            if node_labels.get("rt-cluster") != self.cluster_name:
                continue
            if node.get("state") in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            name = node.get("name", "").rsplit("/", 1)[-1]
            node_type = node_labels.get("rt-node-type", "")
            # num_hosts from OUR node-type spec, not networkEndpoints: a
            # CREATING slice has no endpoints yet, and under-reporting host
            # count breaks the autoscaler's booting/slot accounting
            # (double-provisioning, mid-boot idle reaping).
            spec_hosts = self.node_types.get(node_type, {}).get("num_hosts")
            out.append({
                "provider_node_id": name,
                "node_type": node_type,
                "labels": {LABEL_SLICE_NAME: name,
                           LABEL_SLICE_TOPOLOGY: node.get(
                               "acceleratorConfig", {}).get("topology", ""),
                           **node_labels},
                "created_at": node.get("createTime", 0) or 0,
                "num_hosts": (spec_hosts
                              or len(node.get("networkEndpoints", []))
                              or 1),
            })
        return out


class FakeTpuRestHttp:
    """In-memory TPU REST API double that BOOTS real local nodes.

    Reference analog: ``autoscaler/_private/fake_multi_node/node_provider.py``
    — fake the cloud, keep everything below it real. A create "provisions"
    ``num_hosts`` × ``node_main`` daemons (one per pod-slice host, each with
    the slice's topology labels), so the autoscaler test exercises the
    actual join/heartbeat/scheduling path; a delete terminates them.
    ``shapes`` maps accelerator_type -> (num_hosts, chips_per_host).
    """

    def __init__(self, gcs_address: str,
                 shapes: Dict[str, Tuple[int, int]],
                 cpus_per_host: float = 1):
        self.gcs_address = gcs_address
        self.shapes = dict(shapes)
        self.cpus_per_host = cpus_per_host
        self.nodes: Dict[str, Dict] = {}       # slice name -> REST node dict
        self._procs: Dict[str, List] = {}      # slice name -> host processes
        self.requests: List[Tuple[str, str]] = []

    # -- the HttpFn ----------------------------------------------------------
    def __call__(self, method: str, url: str, headers: Dict[str, str],
                 body: Optional[Dict]) -> Tuple[int, Dict]:
        assert headers.get("Authorization", "").startswith("Bearer "), \
            "request without auth token"
        path = url.split("/v2/", 1)[1]
        self.requests.append((method, path))
        if method == "POST" and "?nodeId=" in path:
            name = path.split("?nodeId=", 1)[1]
            return self._create(name, body)
        if method == "DELETE":
            return self._delete(path.rsplit("/", 1)[-1])
        if method == "GET" and path.endswith("/nodes"):
            return 200, {"nodes": [dict(n) for n in self.nodes.values()]}
        if method == "GET":
            name = path.rsplit("/", 1)[-1]
            if name not in self.nodes:
                return 404, {"error": "not found"}
            return 200, dict(self.nodes[name])
        return 400, {"error": f"unhandled {method} {path}"}

    def _create(self, name: str, body: Dict) -> Tuple[int, Dict]:
        if name in self.nodes:
            return 409, {"error": "already exists"}
        # Mirror the real API contract: exactly one accelerator field.
        acc = body.get("acceleratorType", "")
        topology = body.get("acceleratorConfig", {}).get("topology", "")
        if bool(acc) == bool(topology):
            return 400, {"error": "exactly one of acceleratorType / "
                                  "acceleratorConfig must be set"}
        key = acc or topology    # shapes may be keyed by either form
        if key not in self.shapes:
            return 400, {"error": f"unknown accelerator shape {key!r}"}
        num_hosts, chips = self.shapes[key]
        self._boot_hosts(name, topology, num_hosts, chips)
        self.nodes[name] = {
            "name": name, "state": "READY",
            "acceleratorType": acc,
            "acceleratorConfig": body.get("acceleratorConfig", {}),
            "labels": dict(body.get("labels", {})),
            "createTime": time.time(),
            "networkEndpoints": [{"ipAddress": f"10.0.0.{i}"}
                                 for i in range(num_hosts)],
        }
        return 200, {"name": f"operations/create-{name}", "done": True}

    def _delete(self, name: str) -> Tuple[int, Dict]:
        if name not in self.nodes:
            return 404, {"error": "not found"}
        for proc in self._procs.pop(name, []):
            proc.terminate()
        self.nodes.pop(name)
        return 200, {"name": f"operations/delete-{name}", "done": True}

    def _boot_hosts(self, slice_name: str, topology: str, num_hosts: int,
                    chips: int) -> None:
        import os
        import subprocess
        import sys

        import ray_tpu

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = ":".join(
            [repo_root] + [p for p in env.get("PYTHONPATH", "").split(":")
                           if p])
        procs = []
        for worker_id in range(num_hosts):
            labels = {LABEL_SLICE_NAME: slice_name,
                      LABEL_SLICE_TOPOLOGY: topology,
                      "tpu-worker-id": str(worker_id)}
            args = [sys.executable, "-m", "ray_tpu.cluster.node_main",
                    "--address", self.gcs_address,
                    "--num-cpus", str(self.cpus_per_host),
                    "--num-tpus", str(chips),
                    "--labels", json.dumps(labels)]
            procs.append(subprocess.Popen(
                args, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, start_new_session=True))
        self._procs[slice_name] = procs

    def shutdown(self) -> None:
        for name in list(self.nodes):
            self._delete(name)
