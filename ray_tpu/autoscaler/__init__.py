"""Demand-driven cluster autoscaling.

Reference analog: ``python/ray/autoscaler/_private/`` —
``StandardAutoscaler`` (``autoscaler.py:166``), ``LoadMetrics``
(``load_metrics.py:63``), ``ResourceDemandScheduler``
(``resource_demand_scheduler.py:102``) and the ``NodeProvider`` plugin API
(``autoscaler/node_provider.py:13``). Redesign: no SSH updater — providers
launch node daemons that self-register with the GCS (``rt start
--address=...`` semantics); the local provider runs REAL raylet daemons as
subprocesses, the ``ray_tpu`` answer to the reference's
``FakeMultiNodeProvider`` (which only faked provisioning).
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.instance_manager import (  # noqa: F401
    Instance,
    InstanceManager,
    InstanceStorage,
)
from ray_tpu.autoscaler.gcp import (  # noqa: F401
    FakeTpuRestHttp,
    GcpTpuPodProvider,
    TpuRestClient,
)
from ray_tpu.autoscaler.gke import (  # noqa: F401
    FakeK8sHttp,
    GkeTpuPodProvider,
    K8sClient,
)
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    LocalNodeProvider,
    NodeProvider,
)
