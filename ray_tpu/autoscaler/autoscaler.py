"""StandardAutoscaler: reconcile node count against unplaced demand.

Reference analog: ``autoscaler/_private/autoscaler.py:166``
(StandardAutoscaler) + ``resource_demand_scheduler.py:102`` (bin-pack the
pending demand onto hypothetical node types) + ``monitor.py:126`` (the loop).
Scale-up: queued demands that no node can currently satisfy are greedily
packed onto the cheapest feasible node type. Scale-down: a provider node
idle (available == total) past ``idle_timeout_s`` and above
``min_workers`` is terminated.

Config shape (the cluster-YAML essentials):
  {"min_workers": 0, "max_workers": 8, "idle_timeout_s": 60.0,
   "node_types": {"cpu4": {"resources": {"CPU": 4}, "max_workers": 8},
                  "tpu_v5e_4": {"resources": {"CPU": 8, "TPU": 4}}}}

TPU note: a node type with a ``TPU`` resource is a whole slice-host — the
gang demand of a SliceGroup/placement group appears as queued bundles and
provisions whole hosts, the reference's ``autoscaler/gcp/tpu.yaml`` flow.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(resources: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in demand.items())


def _subtract(resources: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        resources[k] = resources.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: Dict, provider: NodeProvider,
                 gcs_address: str, update_interval_s: float = 2.0):
        self.config = dict(config)
        self.provider = provider
        self.gcs_address = gcs_address
        self.update_interval_s = update_interval_s
        self._idle_since: Dict[str, float] = {}   # provider_node_id -> t
        self._boot_since: Dict[str, float] = {}   # provider_node_id -> t
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_error: Optional[str] = None

    # ---- GCS access ---------------------------------------------------------
    def _cluster_load(self) -> List[Dict]:
        from ray_tpu.cluster.rpc import RpcClient

        async def _go():
            client = RpcClient(self.gcs_address, peer_id="autoscaler")
            await client.connect()
            try:
                return await client.call("cluster_load", {}, timeout=10.0)
            finally:
                await client.close()

        return asyncio.run(_go())

    # ---- one reconcile pass -------------------------------------------------
    def update(self) -> Dict[str, int]:
        """Returns {"launched": n, "terminated": m} for observability."""
        load = [n for n in self._cluster_load() if n["alive"]]
        provider_nodes = self.provider.non_terminated_nodes()
        reaped = self._reap_boot_failures(load, provider_nodes)
        if reaped:
            provider_nodes = self.provider.non_terminated_nodes()
        launched = self._scale_up(load, provider_nodes)
        terminated = self._scale_down(load, provider_nodes)
        return {"launched": launched, "terminated": terminated + reaped}

    def _booting(self, p: Dict, load: List[Dict]) -> bool:
        """Provider node created but not all hosts joined the GCS yet."""
        return len(self._gcs_nodes_for(p, load)) < p.get("num_hosts", 1)

    def _reap_boot_failures(self, load: List[Dict],
                            provider_nodes: List[Dict]) -> int:
        """Terminate nodes whose hosts never joined within boot_timeout_s.

        Without this, a slice whose startup script fails would count as
        in-flight headroom forever: scale-up sees the demand "covered",
        scale-down sees "still booting", and the cluster deadlocks with a
        billed, useless slice.
        """
        boot_timeout = self.config.get("boot_timeout_s", 600.0)
        now = time.time()
        reaped = 0
        for p in provider_nodes:
            pid = p["provider_node_id"]
            if not self._booting(p, load):
                self._boot_since.pop(pid, None)
                continue
            self._boot_since.setdefault(pid, now)
            if now - self._boot_since[pid] >= boot_timeout:
                self._last_error = (f"node {pid} failed to join within "
                                    f"{boot_timeout}s; terminating")
                self.provider.terminate_node(pid)
                self._boot_since.pop(pid, None)
                reaped += 1
        return reaped

    def _scale_up(self, load: List[Dict], provider_nodes: List[Dict]) -> int:
        # unsatisfied demand = queued requests no node could run NOW.
        # Each demand is (resources, anti_affinity_group): bundles of a
        # STRICT_SPREAD gang carry their PG id so the bin-pack never counts
        # two of them against ONE node's headroom (they could never commit
        # there — reference: resource_demand_scheduler carries PG strategy).
        demands: List[tuple] = []
        for n in load:
            for d in n.get("queued_demands", []):
                item = (dict(d["resources"]), d.get("strict_spread_group"))
                demands.extend([item] * int(d["count"]))
        if not demands:
            return 0
        headroom = [{"res": dict(n["available"]), "groups": []}
                    for n in load]
        # In-flight capacity: provider nodes that haven't joined the GCS yet
        # (cloud slices provision asynchronously — create returns before the
        # hosts boot). Count their full spec as headroom or every reconcile
        # tick during boot would launch ANOTHER slice for the same demand
        # (reference: resource_demand_scheduler's pending-launch accounting).
        node_types = self.config.get("node_types", {})
        for p in provider_nodes:
            if self._booting(p, load):
                spec = node_types.get(p.get("node_type"))
                if spec:
                    # Only the NOT-yet-joined hosts' share: joined hosts
                    # already contribute real headroom through the load
                    # report — counting the full spec would double-count a
                    # partially-joined slice's capacity.
                    hosts = max(1, p.get("num_hosts", 1))
                    joined = len(self._gcs_nodes_for(p, load))
                    missing = max(0, hosts - joined)
                    frac = missing / hosts
                    headroom.append({
                        "res": {k: v * frac
                                for k, v in spec["resources"].items()},
                        "groups": [],
                        "slots": missing,
                    })

        def try_place(entry, res, group) -> bool:
            if not _fits(entry["res"], res):
                return False
            if group is not None:
                if entry["groups"].count(group) >= entry.get("slots", 1):
                    return False
            _subtract(entry["res"], res)
            if group is not None:
                entry["groups"].append(group)
            return True

        unsatisfied: List[tuple] = []
        for res, group in demands:
            if not any(try_place(h, res, group) for h in headroom):
                unsatisfied.append((res, group))
        if not unsatisfied:
            return 0

        max_workers = self.config.get("max_workers", 8)
        current = len(provider_nodes)
        launched = 0
        # greedy: pack unsatisfied demand onto new nodes of the first
        # feasible type (reference packs via utilization scores; the greedy
        # first-fit keeps v1 predictable)
        while unsatisfied and current + launched < max_workers:
            res0, _ = unsatisfied[0]
            chosen = None
            for type_name, spec in node_types.items():
                if _fits(spec["resources"], res0):
                    per_type = sum(1 for p in provider_nodes
                                   if p["node_type"] == type_name)
                    if per_type + launched < spec.get("max_workers",
                                                      max_workers):
                        chosen = (type_name, spec)
                        break
            if chosen is None:
                break  # no type can EVER satisfy this request
            type_name, spec = chosen
            try:
                self.provider.create_node(
                    type_name, spec["resources"],
                    {"autoscaler_node_type": type_name})
            except Exception as e:  # noqa: BLE001 — cloud errors: retry later
                self._last_error = repr(e)
                break
            launched += 1
            # drain every demand this new node absorbs
            head = {"res": dict(spec["resources"]), "groups": [],
                    "slots": spec.get("num_hosts", 1)}
            unsatisfied = [(res, group) for res, group in unsatisfied
                           if not try_place(head, res, group)]
        return launched

    def _gcs_nodes_for(self, p: Dict, load: List[Dict]) -> List[Dict]:
        """GCS nodes belonging to one provider node. A single-host provider
        records the gcs_node_id it saw at boot; a pod-slice provider can't
        (hosts join asynchronously), so its hosts are found by the
        tpu-slice-name label they registered with."""
        from ray_tpu.core.resources import LABEL_SLICE_NAME

        gid = p.get("gcs_node_id")
        if gid is not None:
            return [n for n in load if n["node_id"] == gid]
        slice_name = p.get("labels", {}).get(LABEL_SLICE_NAME)
        if slice_name:
            return [n for n in load
                    if n.get("labels", {}).get(LABEL_SLICE_NAME) == slice_name]
        return []

    def _scale_down(self, load: List[Dict], provider_nodes: List[Dict]) -> int:
        min_workers = self.config.get("min_workers", 0)
        idle_timeout = self.config.get("idle_timeout_s", 60.0)
        now = time.time()
        removable = []
        for p in provider_nodes:
            gnodes = self._gcs_nodes_for(p, load)
            # A slice is idle only if ALL its hosts have joined AND all are
            # idle — a partially-joined slice must not start the idle clock
            # (boot skew would get it reaped mid-boot; boot failures are
            # _reap_boot_failures' job, on the longer boot timeout).
            idle = (len(gnodes) >= p.get("num_hosts", 1)) and all(
                g["available"] == g["total"] and not g.get("queued_demands")
                for g in gnodes)
            if idle:
                self._idle_since.setdefault(p["provider_node_id"], now)
                if now - self._idle_since[p["provider_node_id"]] >= idle_timeout:
                    removable.append(p["provider_node_id"])
            else:
                self._idle_since.pop(p["provider_node_id"], None)
        terminated = 0
        for pid in removable:
            if len(provider_nodes) - terminated <= min_workers:
                break
            self.provider.terminate_node(pid)
            self._idle_since.pop(pid, None)
            terminated += 1
        return terminated

    # ---- loop ---------------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-autoscaler")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                self._last_error = repr(e)
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
