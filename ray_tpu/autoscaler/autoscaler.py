"""StandardAutoscaler: reconcile node count against unplaced demand.

Reference analog: ``autoscaler/_private/autoscaler.py:166``
(StandardAutoscaler) + ``resource_demand_scheduler.py:102`` (bin-pack the
pending demand onto hypothetical node types) + ``monitor.py:126`` (the loop).
Scale-up: queued demands that no node can currently satisfy are greedily
packed onto the cheapest feasible node type. Scale-down: a provider node
idle (available == total) past ``idle_timeout_s`` and above
``min_workers`` is terminated.

Config shape (the cluster-YAML essentials):
  {"min_workers": 0, "max_workers": 8, "idle_timeout_s": 60.0,
   "node_types": {"cpu4": {"resources": {"CPU": 4}, "max_workers": 8},
                  "tpu_v5e_4": {"resources": {"CPU": 8, "TPU": 4}}}}

TPU note: a node type with a ``TPU`` resource is a whole slice-host — the
gang demand of a SliceGroup/placement group appears as queued bundles and
provisions whole hosts, the reference's ``autoscaler/gcp/tpu.yaml`` flow.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(resources: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in demand.items())


def _subtract(resources: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        resources[k] = resources.get(k, 0.0) - v


class StandardAutoscaler:
    def __init__(self, config: Dict, provider: NodeProvider,
                 gcs_address: str, update_interval_s: float = 2.0):
        self.config = dict(config)
        self.provider = provider
        self.gcs_address = gcs_address
        self.update_interval_s = update_interval_s
        self._idle_since: Dict[str, float] = {}   # provider_node_id -> t
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_error: Optional[str] = None

    # ---- GCS access ---------------------------------------------------------
    def _cluster_load(self) -> List[Dict]:
        from ray_tpu.cluster.rpc import RpcClient

        async def _go():
            client = RpcClient(self.gcs_address, peer_id="autoscaler")
            await client.connect()
            try:
                return await client.call("cluster_load", {}, timeout=10.0)
            finally:
                await client.close()

        return asyncio.run(_go())

    # ---- one reconcile pass -------------------------------------------------
    def update(self) -> Dict[str, int]:
        """Returns {"launched": n, "terminated": m} for observability."""
        load = [n for n in self._cluster_load() if n["alive"]]
        provider_nodes = self.provider.non_terminated_nodes()
        launched = self._scale_up(load, provider_nodes)
        terminated = self._scale_down(load, provider_nodes)
        return {"launched": launched, "terminated": terminated}

    def _scale_up(self, load: List[Dict], provider_nodes: List[Dict]) -> int:
        # unsatisfied demand = queued requests no node could run NOW
        demands: List[Dict[str, float]] = []
        for n in load:
            for d in n.get("queued_demands", []):
                demands.extend([dict(d["resources"])] * int(d["count"]))
        if not demands:
            return 0
        headroom = [dict(n["available"]) for n in load]
        unsatisfied: List[Dict[str, float]] = []
        for demand in demands:
            placed = False
            for h in headroom:
                if _fits(h, demand):
                    _subtract(h, demand)
                    placed = True
                    break
            if not placed:
                unsatisfied.append(demand)
        if not unsatisfied:
            return 0

        max_workers = self.config.get("max_workers", 8)
        current = len(provider_nodes)
        launched = 0
        node_types = self.config.get("node_types", {})
        # greedy: pack unsatisfied demand onto new nodes of the first
        # feasible type (reference packs via utilization scores; the greedy
        # first-fit keeps v1 predictable)
        while unsatisfied and current + launched < max_workers:
            demand = unsatisfied[0]
            chosen = None
            for type_name, spec in node_types.items():
                if _fits(spec["resources"], demand):
                    per_type = sum(1 for p in provider_nodes
                                   if p["node_type"] == type_name)
                    if per_type + launched < spec.get("max_workers",
                                                      max_workers):
                        chosen = (type_name, spec)
                        break
            if chosen is None:
                break  # no type can EVER satisfy this request
            type_name, spec = chosen
            try:
                self.provider.create_node(
                    type_name, spec["resources"],
                    {"autoscaler_node_type": type_name})
            except Exception as e:  # noqa: BLE001 — cloud errors: retry later
                self._last_error = repr(e)
                break
            launched += 1
            # drain every demand this new node absorbs
            head = dict(spec["resources"])
            still = []
            for d in unsatisfied:
                if _fits(head, d):
                    _subtract(head, d)
                else:
                    still.append(d)
            unsatisfied = still
        return launched

    def _scale_down(self, load: List[Dict], provider_nodes: List[Dict]) -> int:
        min_workers = self.config.get("min_workers", 0)
        idle_timeout = self.config.get("idle_timeout_s", 60.0)
        by_gcs_id = {n["node_id"]: n for n in load}
        now = time.time()
        removable = []
        for p in provider_nodes:
            gnode = by_gcs_id.get(p.get("gcs_node_id"))
            idle = (gnode is not None
                    and gnode["available"] == gnode["total"]
                    and not gnode.get("queued_demands"))
            if idle:
                self._idle_since.setdefault(p["provider_node_id"], now)
                if now - self._idle_since[p["provider_node_id"]] >= idle_timeout:
                    removable.append(p["provider_node_id"])
            else:
                self._idle_since.pop(p["provider_node_id"], None)
        terminated = 0
        for pid in removable:
            if len(provider_nodes) - terminated <= min_workers:
                break
            self.provider.terminate_node(pid)
            self._idle_since.pop(pid, None)
            terminated += 1
        return terminated

    # ---- loop ---------------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-autoscaler")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.update()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                self._last_error = repr(e)
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
