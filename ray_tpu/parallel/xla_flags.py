"""TPU/XLA performance flags: latency-hiding scheduler + async collectives.

SURVEY.md §7 "Matching A100/NCCL" calls these out as required for the
headline number: overlap of ICI collectives with compute comes from XLA's
latency-hiding scheduler and the async-collective fusion passes, which are
OFF by default and enabled via ``LIBTPU_INIT_ARGS`` (TPU runtime flags must
be set BEFORE the backend initializes — i.e. before the first jax call in
the process, which is why these are env-var plumbing, not jax.config calls).

The flag set follows the public MaxText/scaling-book recipe:
  - ``xla_tpu_enable_latency_hiding_scheduler`` — schedule compute into the
    shadow of in-flight collectives instead of barriering on them;
  - async collective fusion (+ all-gather / multiple-steps variants) — let
    fsdp all-gathers for layer i+1 overlap layer i's matmuls inside the
    ``lax.scan`` over stacked layers;
  - ``xla_tpu_overlap_compute_collective_tc`` — tensor-core/collective
    overlap on newer generations.

Reference analog: the NCCL env tuning Ray Train applies around its process
group (``/root/reference/python/ray/train/torch/config.py:50``
``NCCL_SOCKET_IFNAME`` etc.) — there the transport is tuned per-process via
env vars too; here the "transport" is the XLA scheduler.
"""

from __future__ import annotations

import os
from typing import MutableMapping, Optional

TPU_PERF_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_tpu_enable_all_experimental_scheduler_features=false",
    # NOT set (libtpu rejects `=true` for them as "flag type mismatch:
    # enum" on current stacks, failing EVERY compile in the process):
    # xla_enable_async_all_gather, xla_enable_async_collective_permute,
    # xla_tpu_enable_async_collective_fusion_fuse_all_gather. Recent XLA
    # schedules async collectives through the latency-hiding-scheduler
    # pipeline, so the flags above carry the overlap behavior.
)


def apply_tpu_perf_flags(env: Optional[MutableMapping[str, str]] = None,
                         ) -> MutableMapping[str, str]:
    """Merge the perf flags into ``LIBTPU_INIT_ARGS`` (idempotent).

    Mutates and returns ``env`` (default ``os.environ``). A flag already
    present in the env — e.g. a user override setting it ``=false`` — wins;
    only missing flags are appended. No-op for flags whose key is present.
    Must run before the process's first jax/libtpu initialization to have
    any effect.
    """
    env = os.environ if env is None else env
    existing = env.get("LIBTPU_INIT_ARGS", "")
    have = {f.split("=", 1)[0] for f in existing.split() if f}
    added = [f for f in TPU_PERF_FLAGS if f.split("=", 1)[0] not in have]
    if added:
        env["LIBTPU_INIT_ARGS"] = " ".join(
            ([existing] if existing else []) + added)
    return env
