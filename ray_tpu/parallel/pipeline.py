"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

TPU-first design: the pipeline is a single SPMD program — every rank runs the
same ``lax.scan`` over ticks; activations hop to the next stage with
``lax.ppermute`` (one ICI neighbor hop per tick). No per-stage processes, no
host round-trips: XLA overlaps the permute with the next tick's compute. The
reference has no pipeline parallelism of its own (it delegates to
torch/DeepSpeed — SURVEY.md §2.3 "other backends"); here it is a mesh axis
(``pp``) like any other.

Bubble fraction is (P-1)/(M+P-1) for M microbatches on P stages — pick
M >= 4*P for <20% bubble (GPipe schedule; 1F1B would need per-stage weight
stashes, which conflicts with donation — revisit if pp becomes the flagship
axis).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_spmd(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  x: jax.Array,
                  axis_name: str,
                  num_microbatches: int) -> jax.Array:
    """Run ``x`` through P pipeline stages (call INSIDE shard_map).

    ``stage_fn(stage_params, mb)``: this rank's slice of the network applied
    to one microbatch. ``x``: per-shard [B, ...]; B must divide by
    ``num_microbatches``. Returns the final-stage output, replicated to all
    pp ranks (so downstream loss code is rank-agnostic). Differentiable.
    """
    p = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by {m} microbatches")
    xs = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    ticks = m + p - 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        outputs, recv = carry
        # Stage r works on microbatch (t - r); rank 0 reads fresh input.
        in_idx = jnp.clip(t, 0, m - 1)
        x0 = lax.dynamic_index_in_dim(xs, in_idx, 0, keepdims=False)
        x_in = jnp.where(r == 0, x0, recv).astype(xs.dtype)
        y = stage_fn(stage_params, x_in)
        # Last stage finishes microbatch (t - (p-1)).
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        valid = (t >= p - 1) & (r == p - 1)
        prev = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, y, prev), out_idx, 0)
        recv = lax.ppermute(y, axis_name, perm)
        return (outputs, recv), None

    out0 = jnp.zeros((m, *xs.shape[1:]), x.dtype)
    (outputs, _), _ = lax.scan(tick, (out0, jnp.zeros_like(xs[0])),
                               jnp.arange(ticks))
    # Outputs live on the last rank; replicate so every rank returns them.
    outputs = lax.psum(jnp.where(r == p - 1, outputs, 0.0), axis_name)
    return outputs.reshape(x.shape)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   params: Any,
                   x: jax.Array,
                   mesh: Mesh,
                   *,
                   axis_name: str = "pp",
                   num_microbatches: int = 4,
                   batch_axes: Tuple = (("dp", "fsdp"),),
                   param_layer_axis: int = 0,
                   remat: bool = True) -> jax.Array:
    """Jit-level pipeline entry: shard_map over ``axis_name``.

    ``params``: pytree whose leaves stack ALL layers on ``param_layer_axis``
    (the llama layout); the leading axis is split across pp ranks, so each
    rank's ``stage_fn`` sees [L/P, ...] leaves and scans over them.
    ``x``: global activations [B, ...] (batch sharded over ``batch_axes``).
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    pspec = jax.tree.map(
        lambda _: P(*([None] * param_layer_axis), axis_name), params)
    xspec = P(*batch_axes)

    def body(pp, xx):
        return pipeline_spmd(fn, pp, xx, axis_name, num_microbatches)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, xspec),
        out_specs=xspec,
        check_vma=False,
    )(params, x)
