"""Pipeline parallelism: microbatch pipelining over a mesh axis.

TPU-first design: the pipeline is a single SPMD program — every rank runs the
same ``lax.scan`` over ticks; activations hop to the next stage with
``lax.ppermute`` (one ICI neighbor hop per tick). No per-stage processes, no
host round-trips: XLA overlaps the permute with the next tick's compute. The
reference has no pipeline parallelism of its own (it delegates to
torch/DeepSpeed — SURVEY.md §2.3 "other backends"); here it is a mesh axis
(``pp``) like any other.

Two schedules:
  - **GPipe** (``pipeline_spmd``/``pipeline_apply``): forward scan, backward
    by autodiff of the scan. Activation stash grows with M (all microbatch
    inputs live until the transposed scan consumes them) — simple, fully
    differentiable, good for small M.
  - **1F1B** (``pipeline_1f1b``): forward AND backward interleaved in one
    scan — every tick runs one stage forward and one per-stage ``jax.vjp``
    backward on an older microbatch, so at most 2P-1 microbatch inputs are
    ever stashed, independent of M. That O(P) activation memory is what lets
    M (and therefore utilization) scale: at a fixed stash budget, 1F1B runs
    a much larger M and a smaller bubble fraction than GPipe (see
    ``schedule_stats``). Cost: the loss head is evaluated on every rank
    (cotangent-masked to the last stage) — a few percent of stage FLOPs.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.context import axis_size, shard_map


def _microbatch(tree: Any, m: int):
    """Reshape every [B, ...] leaf to [m, B/m, ...]."""
    return jax.tree.map(
        lambda a: a.reshape(m, a.shape[0] // m, *a.shape[1:]), tree)


def _mb_index(tree: Any, idx):
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, idx, 0, keepdims=False), tree)


def pipeline_spmd(stage_fn: Callable,
                  stage_params: Any,
                  x: jax.Array,
                  axis_name: str,
                  num_microbatches: int,
                  extras: Any = None) -> jax.Array:
    """Run ``x`` through P pipeline stages (call INSIDE shard_map).

    ``stage_fn(stage_params, mb)`` — or ``stage_fn(stage_params, mb,
    extras_mb)`` when ``extras`` is given: this rank's slice of the network
    applied to one microbatch. ``x``: per-shard [B, ...]; B must divide by
    ``num_microbatches``. ``extras``: optional pytree of [B, ...] arrays
    (segment ids, positions) — microbatched alongside ``x`` but indexed
    locally per tick rather than transported through the pipe (every rank
    holds the full batch copy of them). Returns the final-stage output,
    replicated to all pp ranks (so downstream loss code is rank-agnostic).
    Differentiable.
    """
    p = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m:
        raise ValueError(f"batch {x.shape[0]} not divisible by {m} microbatches")
    xs = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    exs = None if extras is None else _microbatch(extras, m)
    ticks = m + p - 1
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        outputs, recv = carry
        # Stage r works on microbatch (t - r); rank 0 reads fresh input.
        in_idx = jnp.clip(t, 0, m - 1)
        x0 = lax.dynamic_index_in_dim(xs, in_idx, 0, keepdims=False)
        x_in = jnp.where(r == 0, x0, recv).astype(xs.dtype)
        if exs is None:
            y = stage_fn(stage_params, x_in)
        else:
            # This rank is on microbatch (t - r) — index ITS extras, not
            # rank 0's input index.
            my_idx = jnp.clip(t - r, 0, m - 1)
            y = stage_fn(stage_params, x_in, _mb_index(exs, my_idx))
        # Last stage finishes microbatch (t - (p-1)).
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        valid = (t >= p - 1) & (r == p - 1)
        prev = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, y, prev), out_idx, 0)
        recv = lax.ppermute(y, axis_name, perm)
        return (outputs, recv), None

    out0 = jnp.zeros((m, *xs.shape[1:]), x.dtype)
    (outputs, _), _ = lax.scan(tick, (out0, jnp.zeros_like(xs[0])),
                               jnp.arange(ticks))
    # Outputs live on the last rank; replicate so every rank returns them.
    outputs = lax.psum(jnp.where(r == p - 1, outputs, 0.0), axis_name)
    return outputs.reshape(x.shape)


def pipeline_1f1b(stage_fn: Callable,
                  head_loss_fn: Callable,
                  layer_params: Any,
                  head_params: Any,
                  x: jax.Array,
                  targets: jax.Array,
                  mesh: Mesh,
                  *,
                  axis_name: str = "pp",
                  num_microbatches: int = 4,
                  batch_axes: Tuple = ("dp", "fsdp", "tp"),
                  segments: Optional[jax.Array] = None,
                  loss_mask: Optional[jax.Array] = None):
    """Interleaved forward/backward (1F1B) pipeline with manual per-stage
    VJPs. Returns ``(loss, layer_grads, head_grads, x_grads)``.

    Schedule: one ``lax.scan`` over T = M + 2P - 1 ticks. At tick t, rank r
    runs the FORWARD of microbatch ``t - r`` and the BACKWARD (a
    ``jax.vjp`` of stage+loss, i.e. recompute-forward + backward — full
    rematerialization by construction) of microbatch ``t - 2P + 1 + r``;
    activations hop forward and cotangents hop backward via ``ppermute``
    each tick. A microbatch input is stashed for the 2P-1-2r ticks between
    its F and B on a rank, so peak stash is 2P-1 microbatches regardless of
    M — versus M for GPipe-by-autodiff. That is the entire point: memory no
    longer caps M, and bubble fraction falls as M grows.

    The loss head runs inside the pipeline (backward must START there), so
    ``head_loss_fn(head_params, y_mb, tgt_mb, mask_mb) -> mean_nll`` is
    evaluated by every rank each backward tick with its cotangent masked to
    the last stage — wasted FLOPs bounded by head-cost/stage-cost, the price
    of a uniform SPMD program (a data-dependent branch on rank would lower
    to ``select`` and compute both sides anyway).

    ``layer_params`` leaves are the [L, ...] stacked-layer arrays sharded
    P(axis_name) on dim 0; ``head_params`` replicated; ``x``/``targets``/
    ``segments``/``loss_mask`` batch-sharded over ``batch_axes``.
    """
    m = num_microbatches
    pspec = jax.tree.map(lambda _: P(axis_name), layer_params)
    hspec = jax.tree.map(lambda _: P(), head_params)
    xspec = P(batch_axes)
    dspec = P(batch_axes)

    mask = (jnp.ones(targets.shape, jnp.float32) if loss_mask is None
            else loss_mask.astype(jnp.float32))
    segs = segments  # may be None (captured statically)

    def body(w, head, xx, tt, mm, *rest):
        ss = rest[0] if rest else None
        p = axis_size(axis_name)
        r = lax.axis_index(axis_name)
        if xx.shape[0] % m:
            raise ValueError(
                f"batch {xx.shape[0]} not divisible by {m} microbatches")
        xs = _microbatch(xx, m)
        ts = _microbatch(tt, m)
        ms = _microbatch(mm, m)
        sg = None if ss is None else _microbatch(ss, m)
        mb_shape = xs.shape[1:]
        n_slots = 2 * p - 1
        ticks = m + 2 * p - 1
        # Global token count, known upfront: the loss is a global MEAN, so
        # each microbatch's cotangent is its share cnt_mb/total (grads then
        # come out mean-scaled, matching value_and_grad of lm_loss).
        total_cnt = jnp.maximum(lax.psum(mm.sum(), tuple(batch_axes)), 1.0)
        perm_f = [(i, (i + 1) % p) for i in range(p)]
        perm_b = [(i, (i - 1) % p) for i in range(p)]

        def tick(carry, t):
            stash, f_recv, b_recv, gw, gh, nll, cnt, gx = carry

            # ---- backward STASH READ first: B(m, r=0) at tick m+2P-1 and
            # F(m+2P-1, r=0) share a tick AND a stash slot — the read must
            # see the old microbatch, so it precedes the forward's write.
            mb = t - 2 * (p - 1) + r - 1
            b_valid = (mb >= 0) & (mb < m)
            mb_c = jnp.clip(mb, 0, m - 1)
            a_b = lax.dynamic_index_in_dim(stash, mb_c % n_slots, 0, False)

            # ---- forward: microbatch t - r --------------------------------
            mf = t - r
            f_valid = (mf >= 0) & (mf < m)
            mf_c = jnp.clip(mf, 0, m - 1)
            a_f = jnp.where(r == 0,
                            lax.dynamic_index_in_dim(xs, mf_c, 0, False),
                            f_recv).astype(xs.dtype)
            seg_f = None if sg is None else lax.dynamic_index_in_dim(
                sg, mf_c, 0, False)
            y_f = stage_fn(w, a_f, seg_f)
            slot_f = mf_c % n_slots
            prev = lax.dynamic_index_in_dim(stash, slot_f, 0, False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_valid, a_f, prev), slot_f, 0)

            # ---- backward: microbatch t - 2P + 1 + r ----------------------
            tgt_b = lax.dynamic_index_in_dim(ts, mb_c, 0, False)
            msk_b = lax.dynamic_index_in_dim(ms, mb_c, 0, False)
            seg_b = None if sg is None else lax.dynamic_index_in_dim(
                sg, mb_c, 0, False)

            def stage_and_loss(w_, head_, a_):
                y_ = stage_fn(w_, a_, seg_b)
                return y_, head_loss_fn(head_, y_, tgt_b, msk_b)

            (_, mean_nll), vjp = jax.vjp(stage_and_loss, w, head, a_b)
            is_last = r == p - 1
            cnt_b = msk_b.sum()
            # Cotangent routing: interior ranks are driven by the received
            # activation cotangent; the last rank by the loss (scaled
            # mean->sum so microbatch means accumulate exactly).
            g_y = jnp.where(is_last | ~b_valid, 0.0, b_recv).astype(xs.dtype)
            l_cot = jnp.where(is_last & b_valid, cnt_b, 0.0) / total_cnt
            gw_d, gh_d, g_a = vjp((g_y, l_cot))
            gw = jax.tree.map(jnp.add, gw, gw_d)
            gh = jax.tree.map(jnp.add, gh, gh_d)
            picked = is_last & b_valid
            nll = nll + jnp.where(picked, mean_nll * cnt_b, 0.0)
            cnt = cnt + jnp.where(picked, cnt_b, 0.0)
            gx_prev = lax.dynamic_index_in_dim(gx, mb_c, 0, False)
            gx = lax.dynamic_update_index_in_dim(
                gx, jnp.where(b_valid & (r == 0), g_a, gx_prev), mb_c, 0)

            # ---- hop ------------------------------------------------------
            f_recv = lax.ppermute(y_f, axis_name, perm_f)
            b_recv = lax.ppermute(g_a, axis_name, perm_b)
            return (stash, f_recv, b_recv, gw, gh, nll, cnt, gx), None

        init = (
            jnp.zeros((n_slots, *mb_shape), xs.dtype),      # stash
            jnp.zeros(mb_shape, xs.dtype),                  # f_recv
            jnp.zeros(mb_shape, xs.dtype),                  # b_recv
            jax.tree.map(jnp.zeros_like, w),                # gw
            jax.tree.map(jnp.zeros_like, head),             # gh
            jnp.zeros((), jnp.float32),                     # nll sum
            jnp.zeros((), jnp.float32),                     # token count
            jnp.zeros((m, *mb_shape), xs.dtype),            # gx
        )
        carry, _ = lax.scan(tick, init, jnp.arange(ticks))
        _, _, _, gw, gh, nll, cnt, gx = carry

        data_axes = tuple(batch_axes)
        gw = lax.psum(gw, data_axes)                 # DP reduce, not over pp
        gh = lax.psum(gh, data_axes + (axis_name,))  # only last rank nonzero
        nll = lax.psum(nll, data_axes + (axis_name,))
        cnt = lax.psum(cnt, data_axes + (axis_name,))
        gx = lax.psum(gx, (axis_name,))              # only rank 0 nonzero
        loss = nll / jnp.maximum(cnt, 1.0)
        return loss, gw, gh, gx.reshape(xx.shape)

    args = [layer_params, head_params, x, targets, mask]
    specs = [pspec, hspec, xspec, dspec, dspec]
    if segs is not None:
        args.append(segs)
        specs.append(dspec)
    return shard_map(
        body, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=(P(), pspec, hspec, xspec),
        check_vma=False,
    )(*args)


def schedule_stats(schedule: str, p: int, m: int) -> dict:
    """Analytic cost model for the two schedules (unit = one stage-forward;
    a backward is 2 units, as is standard).

    Used by tests and capacity planning: at a FIXED activation-stash budget,
    1F1B's O(P) stash admits a much larger M and therefore a smaller bubble
    (idle) fraction — the honest form of the 1F1B claim. At equal M the two
    schedules' total durations are comparable (1F1B's uniform F+B ticks pay
    ~2P extra stage-computes of warmup/cooldown waste; GPipe pays 2(P-1)
    idle), so the win comes entirely from memory-enabled scale-up of M.
    """
    if schedule == "gpipe":
        useful = 3 * m                     # m fwd + m bwd(=2)
        total = 3 * (m + p - 1)            # fwd scan + transposed scan
        return {"ticks": m + p - 1, "stage_computes": total,
                "idle_stage_computes": total - useful,
                "idle_fraction": (total - useful) / total,
                "peak_stash_microbatches": m}
    if schedule == "1f1b":
        ticks = m + 2 * p - 1              # every tick = 1 F + 1 B
        useful = 3 * m
        total = 3 * ticks
        # The kernel statically allocates a 2P-1-slot stash regardless of M
        # (pipeline_1f1b init), so that is the honest planning number.
        return {"ticks": ticks, "stage_computes": total,
                "idle_stage_computes": total - useful,
                "idle_fraction": (total - useful) / total,
                "peak_stash_microbatches": 2 * p - 1}
    raise ValueError(f"unknown schedule {schedule!r}")


def max_microbatches_for_stash(schedule: str, p: int, stash_budget: int) -> int:
    """Largest M whose activation stash fits ``stash_budget`` microbatches."""
    if schedule == "gpipe":
        return stash_budget
    if schedule == "1f1b":
        return 10 ** 9 if stash_budget >= 2 * p - 1 else 0
    raise ValueError(f"unknown schedule {schedule!r}")


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   params: Any,
                   x: jax.Array,
                   mesh: Mesh,
                   *,
                   axis_name: str = "pp",
                   num_microbatches: int = 4,
                   batch_axes: Tuple = (("dp", "fsdp"),),
                   param_layer_axis: int = 0,
                   remat: bool = True,
                   extras: Any = None) -> jax.Array:
    """Jit-level pipeline entry: shard_map over ``axis_name``.

    ``params``: pytree whose leaves stack ALL layers on ``param_layer_axis``
    (the llama layout); the leading axis is split across pp ranks, so each
    rank's ``stage_fn`` sees [L/P, ...] leaves and scans over them.
    ``x``: global activations [B, ...] (batch sharded over ``batch_axes``).
    ``extras``: optional pytree of per-example side inputs (segment ids)
    batch-sharded like ``x`` and fed to ``stage_fn(params, mb, extras_mb)``.
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    pspec = jax.tree.map(
        lambda _: P(*([None] * param_layer_axis), axis_name), params)
    xspec = P(*batch_axes)

    if extras is None:
        def body(pp, xx):
            return pipeline_spmd(fn, pp, xx, axis_name, num_microbatches)

        in_specs = (pspec, xspec)
        args = (params, x)
    else:
        def body(pp, xx, ex):
            return pipeline_spmd(fn, pp, xx, axis_name, num_microbatches,
                                 extras=ex)

        in_specs = (pspec, xspec, jax.tree.map(lambda _: xspec, extras))
        args = (params, x, extras)

    return shard_map(
        body, mesh=mesh,
        in_specs=in_specs,
        out_specs=xspec,
        check_vma=False,
    )(*args)
