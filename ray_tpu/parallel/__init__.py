"""Parallelism as meshes + shardings.

Where the reference reaches for torch DDP/FSDP/DeepSpeed process groups
(``python/ray/train/torch/config.py:64``, ``train_loop_utils.py:91-100``),
this framework expresses every strategy — DP, FSDP/ZeRO, TP, SP/CP, EP, PP —
as a `jax.sharding.Mesh` plus partition rules, letting XLA insert the
ICI/DCN collectives.

Submodules are loaded lazily (PEP 562): ``sharding`` imports jax at module
scope (~2s cold), and eager re-export made EVERY import under the package —
including the jax-free ``xla_flags`` env plumbing that worker processes run
at spawn — pay that cost, slowing worker cold-start enough to starve
latency-sensitive actor calls.
"""

_EXPORTS = {
    "MeshConfig": "ray_tpu.parallel.mesh",
    "make_mesh": "ray_tpu.parallel.mesh",
    "ShardingRules": "ray_tpu.parallel.sharding",
    "named_sharding": "ray_tpu.parallel.sharding",
    "shard_pytree": "ray_tpu.parallel.sharding",
    "make_train_step": "ray_tpu.parallel.train_step",
    "make_multi_step": "ray_tpu.parallel.train_step",
    "shard_batch": "ray_tpu.parallel.train_step",
    "supports_multi_step": "ray_tpu.parallel.train_step",
    "Plan": "ray_tpu.parallel.plan",
    "compile_plan": "ray_tpu.parallel.plan",
    "compile_step": "ray_tpu.parallel.plan",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name])
        val = getattr(mod, name)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
