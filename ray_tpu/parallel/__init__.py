"""Parallelism as meshes + shardings.

Where the reference reaches for torch DDP/FSDP/DeepSpeed process groups
(``python/ray/train/torch/config.py:64``, ``train_loop_utils.py:91-100``),
this framework expresses every strategy — DP, FSDP/ZeRO, TP, SP/CP, EP, PP —
as a `jax.sharding.Mesh` plus partition rules, letting XLA insert the
ICI/DCN collectives.
"""

from ray_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
from ray_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules,
    named_sharding,
    shard_pytree,
)
