"""Device mesh construction for all parallelism strategies.

Axis convention (the scaling-book layout):
  - ``dp``   — pure data parallel (params replicated), maps to DCN across
               slices in multi-slice jobs;
  - ``fsdp`` — data axis whose lanes ALSO shard parameters/optimizer state
               (ZeRO-3); maps to ICI within a slice;
  - ``tp``   — tensor parallel (activations sharded on hidden dims), innermost
               so its all-reduces ride the fastest ICI links;
  - ``sp``   — sequence/context parallel for ring attention;
  - ``ep``   — expert parallel for MoE;
  - ``pp``   — pipeline stages.

Batch is sharded over (dp, fsdp) [and sp for long-context]; params over
(fsdp, tp). Unused axes have size 1 and cost nothing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @classmethod
    def for_devices(cls, n: int, *, tp: int = 1, sp: int = 1, ep: int = 1,
                    pp: int = 1, dp: int = 1) -> "MeshConfig":
        """Fill the fsdp axis with whatever ``n`` leaves over."""
        denom = tp * sp * ep * pp * dp
        if n % denom:
            raise ValueError(f"{n} devices not divisible by tp*sp*ep*pp*dp={denom}")
        return cls(dp=dp, fsdp=n // denom, tp=tp, sp=sp, ep=ep, pp=pp)


def make_mesh(config: MeshConfig,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(config.axis_sizes())
    return Mesh(arr, AXIS_ORDER)


def balanced_factors(n: int, k: int = 3) -> Tuple[int, ...]:
    """Split n into k roughly-balanced integer factors (largest first)."""
    factors = [1] * k
    remaining = n
    for i in range(k - 1):
        f = int(round(remaining ** (1 / (k - i))))
        while f > 1 and remaining % f:
            f -= 1
        factors[i] = max(f, 1)
        remaining //= factors[i]
    factors[k - 1] = remaining
    assert math.prod(factors) == n
    return tuple(sorted(factors, reverse=True))
