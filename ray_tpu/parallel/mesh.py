"""Device mesh construction for all parallelism strategies.

Axis convention (the scaling-book layout):
  - ``dp``   — pure data parallel (params replicated), maps to DCN across
               slices in multi-slice jobs;
  - ``fsdp`` — data axis whose lanes ALSO shard parameters/optimizer state
               (ZeRO-3); maps to ICI within a slice;
  - ``tp``   — tensor parallel (activations sharded on hidden dims), innermost
               so its all-reduces ride the fastest ICI links;
  - ``sp``   — sequence/context parallel for ring attention;
  - ``ep``   — expert parallel for MoE;
  - ``pp``   — pipeline stages.

Batch is sharded over (dp, fsdp) [and sp for long-context]; params over
(fsdp, tp). Unused axes have size 1 and cost nothing.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @classmethod
    def for_devices(cls, n: int, *, tp: int = 1, sp: int = 1, ep: int = 1,
                    pp: int = 1, dp: int = 1) -> "MeshConfig":
        """Fill the fsdp axis with whatever ``n`` leaves over."""
        denom = tp * sp * ep * pp * dp
        if n % denom:
            raise ValueError(f"{n} devices not divisible by tp*sp*ep*pp*dp={denom}")
        return cls(dp=dp, fsdp=n // denom, tp=tp, sp=sp, ep=ep, pp=pp)


def make_mesh(config: MeshConfig,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = config.num_devices
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(config.axis_sizes())
    return Mesh(arr, AXIS_ORDER)


DCN_AXES_DEFAULT = ("dp",)


def make_hybrid_mesh(config: MeshConfig,
                     slice_devices: Sequence[Sequence[jax.Device]],
                     dcn_axes: Sequence[str] = DCN_AXES_DEFAULT) -> Mesh:
    """ICI×DCN hybrid mesh for multi-slice jobs.

    ``slice_devices`` groups the devices by pod slice (equal sizes). The
    ``dcn_axes`` (default: ``dp``) span *slices* — their collectives cross
    the data-center network — while every other axis stays *within* a slice
    so fsdp all-gathers / tp all-reduces / sp permutes ride ICI. This is the
    scaling-book multi-slice recipe (dp-over-DCN outermost); the reference
    encodes the same topology operationally in its TPU pod autoscaler YAMLs
    (``autoscaler/gcp/example-tpu-pod-topology.yaml``) but has no mesh layer
    to consume it.

    The product of the dcn axis sizes must equal ``len(slice_devices)``;
    the remaining axes must use exactly one slice's device count.
    """
    for a in dcn_axes:
        if a not in AXIS_ORDER:
            raise ValueError(f"unknown dcn axis {a!r}")
    n_slices = len(slice_devices)
    dcn_order = [a for a in AXIS_ORDER if a in dcn_axes]
    ici_order = [a for a in AXIS_ORDER if a not in dcn_axes]
    dcn_sizes = [getattr(config, a) for a in dcn_order]
    ici_sizes = [getattr(config, a) for a in ici_order]
    if math.prod(dcn_sizes) != n_slices:
        raise ValueError(
            f"dcn axes {dcn_order} sizes {dcn_sizes} must multiply to the "
            f"slice count {n_slices}")
    per_slice = math.prod(ici_sizes)
    sizes = {len(s) for s in slice_devices}
    if len(sizes) != 1:
        raise ValueError(f"slices must be equal-sized, got {sorted(sizes)}")
    if sizes.pop() != per_slice:
        raise ValueError(
            f"each slice needs exactly {per_slice} devices for axes "
            f"{ici_order} (got {len(slice_devices[0])}); silently idling "
            f"chips is never what you want — shrink/grow the inner axes")

    arr = np.array([list(s) for s in slice_devices],
                   dtype=object).reshape(dcn_sizes + ici_sizes)
    # dims are currently [dcn axes..., ici axes...]; interleave into the
    # canonical AXIS_ORDER so PartitionSpecs are layout-independent.
    current = dcn_order + ici_order
    arr = arr.transpose([current.index(a) for a in AXIS_ORDER])
    return Mesh(arr, AXIS_ORDER)


def hybrid_mesh_from_process_slices(config: MeshConfig,
                                    process_slices: Sequence[str],
                                    devices: Optional[Sequence[jax.Device]]
                                    = None,
                                    dcn_axes: Sequence[str]
                                    = DCN_AXES_DEFAULT) -> Mesh:
    """Hybrid mesh from a process→slice-name assignment.

    ``process_slices[i]`` is the slice name of jax process ``i`` (in a
    TrainWorker gang, rank i == jax process i — ``bootstrap_jax_distributed``
    passes the rank as ``process_id``). Devices are grouped by their owning
    process, processes by slice; slice order on the DCN axis follows first
    appearance in ``process_slices`` so every rank derives the identical
    mesh without coordination.
    """
    devices = list(devices) if devices is not None else jax.devices()
    by_process: dict = {}
    for d in devices:
        by_process.setdefault(d.process_index, []).append(d)
    slice_order: list = []
    slice_procs: dict = {}
    for proc, name in enumerate(process_slices):
        if name not in slice_procs:
            slice_procs[name] = []
            slice_order.append(name)
        slice_procs[name].append(proc)
    slice_devs = [
        [d for p in slice_procs[name] for d in by_process.get(p, [])]
        for name in slice_order
    ]
    return make_hybrid_mesh(config, slice_devs, dcn_axes)


def pg_slice_assignments(pg) -> list:
    """bundle index → slice name, from the bundles' nodes' topology labels.

    Reads each bundle's placed node from the GCS placement-group table and
    that node's ``tpu-slice-name`` label (``core/resources.py``
    LABEL_SLICE_NAME). Nodes without a slice label fall into one synthetic
    slice per node — correct for CPU test clusters where every "slice" is
    one host.
    """
    from ray_tpu.core.resources import LABEL_SLICE_NAME
    from ray_tpu.core.worker import global_worker
    from ray_tpu.util.placement_group import placement_group_table

    backend = global_worker()._require_backend()
    table = {e["pg_id"]: e for e in placement_group_table()}
    entry = table.get(pg.id.hex() if hasattr(pg.id, "hex") else str(pg.id))
    if entry is None:
        raise ValueError(f"placement group {pg.id} not found in GCS")
    node_labels = {n["node_id"]: n.get("labels", {})
                   for n in backend.nodes()}
    assignments = []
    for i, node_id in enumerate(entry["bundle_nodes"]):
        if node_id is None:
            raise ValueError(f"bundle {i} of {pg.id} is not placed yet "
                             f"(pg.wait() first)")
        labels = node_labels.get(node_id, {})
        assignments.append(labels.get(LABEL_SLICE_NAME) or f"@{node_id}")
    return assignments


def mesh_for_slice_group(pg, config: Optional[MeshConfig] = None,
                         dcn_axes: Sequence[str] = DCN_AXES_DEFAULT,
                         devices: Optional[Sequence[jax.Device]] = None
                         ) -> Mesh:
    """Turn a ``slice_group()`` placement group into a hybrid device mesh.

    Maps bundle i to jax process i (the TrainWorker convention: rank i runs
    in bundle i and passes its rank as ``process_id`` to jax.distributed),
    groups processes by slice label, and builds the ICI×DCN mesh. With no
    explicit ``config``, dp spans the slices and fsdp fills each slice.
    """
    process_slices = pg_slice_assignments(pg)
    if config is None:
        devs = list(devices) if devices is not None else jax.devices()
        n_slices = len(dict.fromkeys(process_slices))
        config = MeshConfig.for_devices(len(devs), dp=n_slices)
    return hybrid_mesh_from_process_slices(config, process_slices, devices,
                                           dcn_axes)


def balanced_factors(n: int, k: int = 3) -> Tuple[int, ...]:
    """Split n into k roughly-balanced integer factors (largest first)."""
    factors = [1] * k
    remaining = n
    for i in range(k - 1):
        f = int(round(remaining ** (1 / (k - i))))
        while f > 1 and remaining % f:
            f -= 1
        factors[i] = max(f, 1)
        remaining //= factors[i]
    factors[k - 1] = remaining
    assert math.prod(factors) == n
    return tuple(sorted(factors, reverse=True))
