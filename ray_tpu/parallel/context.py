"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The capability the reference lacks entirely (SURVEY.md §5 "Long-context /
sequence parallelism: absent") built TPU-first:

- **Ring attention**: K/V chunks rotate around the ``sp`` mesh axis via
  ``lax.ppermute`` (neighbor hops = ICI-local); each step computes one
  blockwise-attention chunk with the pallas flash kernel's ``(out, lse)``
  form and merges via streaming log-sum-exp. Peak memory is O(seq/P) per
  chip, enabling million-token contexts. Exact — not an approximation.
- **Ulysses**: ``lax.all_to_all`` re-shards [b, s/P, h, d] -> [b, s, h/P, d]
  so each chip runs full-sequence attention on a head subset; cheaper
  collectives for moderate sequence lengths, bounded by head count.

Both run INSIDE ``shard_map`` over the mesh; ``sequence_parallel_attention``
is the jit-friendly entry that wraps them (mesh from the ambient
``mesh_scope``, set by the Train layer's step builder).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.pallas.flash import (
    NEG_INF,
    flash_attention_with_lse,
    flash_vjp_chunk,
)

_CURRENT_MESH: contextvars.ContextVar[Optional[Mesh]] = contextvars.ContextVar(
    "ray_tpu_mesh", default=None)

if hasattr(jax, "shard_map"):  # jax >= 0.6: top-level, check_vma spelling
    shard_map = jax.shard_map
else:  # jax 0.4.x: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

if hasattr(lax, "axis_size"):  # jax >= 0.6
    axis_size = lax.axis_size
else:  # jax 0.4.x: psum of the literal 1 constant-folds to a concrete int
    def axis_size(axis_name):
        return lax.psum(1, axis_name)


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    """Make ``mesh`` the ambient mesh for model-internal shard_map regions."""
    token = _CURRENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _CURRENT_MESH.reset(token)


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH.get()


def _merge(o1, lse1, o2, lse2):
    """Merge two partial-softmax results; lse: [b,h,s], o: [b,s,h,d]."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w1 = jnp.where(lse1 <= NEG_INF / 2, 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(lse2 <= NEG_INF / 2, 0.0, jnp.exp(lse2 - m_safe))
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    to_o = lambda w: (w / denom_safe).transpose(0, 2, 1)[..., None]
    o = o1 * to_o(w1) + o2 * to_o(w2)
    lse = jnp.where(denom == 0.0, NEG_INF, m_safe + jnp.log(denom_safe))
    return o.astype(o1.dtype), lse


def _ring_perm(axis_name):
    p = axis_size(axis_name)
    return [(i, (i + 1) % p) for i in range(p)]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis_name``.

    Call inside shard_map; q/k/v per-shard [b, s_loc, h, d] holding this
    rank's contiguous sequence chunk (rank r owns positions
    [r*s_loc, (r+1)*s_loc)). Differentiable (custom VJP rotates dk/dv home
    alongside the k/v ring).
    """
    o, _lse = _ring_fwd_loop(q, k, v, axis_name, causal, scale)
    return o


def _ring_fwd_loop(q, k, v, axis_name, causal, scale):
    p = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_loc = q.shape[1]
    b, _, hq, d = q.shape

    o0 = jnp.zeros((b, s_loc, hq, d), jnp.float32)
    lse0 = jnp.full((b, hq, s_loc), NEG_INF, jnp.float32)
    perm = _ring_perm(axis_name)

    def step(carry, t):
        o, lse, kt, vt = carry
        src = (my - t) % p
        q_off = (my - src) * s_loc
        ot, lset = flash_attention_with_lse(
            q, kt, vt, causal=causal, scale=scale, q_offset=q_off)
        o, lse = _merge(o, lse, ot.astype(jnp.float32), lset)
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        return (o, lse, kt, vt), None

    (o, lse, _, _), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(p))
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_fwd_loop(q, k, v, axis_name, causal, scale)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    p = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_loc = q.shape[1]
    perm = _ring_perm(axis_name)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)

    def step(carry, t):
        dq, kt, vt, dkt, dvt = carry
        src = (my - t) % p
        q_off = (my - src) * s_loc
        dq_c, dk_c, dv_c = flash_vjp_chunk(
            q, kt, vt, o, do, lse, q_offset=q_off, causal=causal, scale=scale)
        dq = dq + dq_c.astype(jnp.float32)
        dkt = dkt + dk_c.astype(jnp.float32)
        dvt = dvt + dv_c.astype(jnp.float32)
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        dkt = lax.ppermute(dkt, axis_name, perm)
        dvt = lax.ppermute(dvt, axis_name, perm)
        return (dq, kt, vt, dkt, dvt), None

    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(p))
    # After p steps + p rotations the accumulators are back at the rank that
    # owns their k/v chunk.
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None,
                      use_flash: bool = True):
    """All-to-all sequence parallelism: re-shard seq->heads, attend, undo.

    Per-shard q: [b, s/P, hq, d]. Requires hq % P == 0; kv heads are
    repeated up to hq first if P doesn't divide them (GQA). Differentiable
    through ``lax.all_to_all``.
    """
    p = axis_size(axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % p:
        raise ValueError(f"ulysses: q heads {hq} not divisible by sp={p}")
    if hkv % p:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    a2a = lambda x: lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                   tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    if use_flash:
        from ray_tpu.ops.pallas.flash import flash_attention
        og = flash_attention(qg, kg, vg, causal=causal, scale=scale)
    else:
        from ray_tpu.ops.attention import mha
        og = mha(qg, kg, vg, causal=causal, scale=scale)
    return lax.all_to_all(og, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def sequence_parallel_attention(q, k, v, *,
                                impl: str = "ring",
                                axis_name: str = "sp",
                                mesh: Optional[Mesh] = None,
                                causal: bool = True,
                                scale: Optional[float] = None):
    """Jit-level entry: shard_map the chosen SP attention over the mesh.

    q/k/v are GLOBAL [b, s, h, d] (seq sharded over ``axis_name`` by GSPMD);
    batch rides (dp, fsdp), heads ride tp. Grad-capable.
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError(
            "sequence_parallel_attention needs a mesh (use parallel.context."
            "mesh_scope(mesh) around the step, or pass mesh=).")
    qspec = P(("dp", "fsdp"), axis_name, "tp", None)

    def local(qq, kk, vv):
        if impl == "ring":
            return ring_attention(qq, kk, vv, axis_name, causal, scale)
        elif impl == "ulysses":
            return ulysses_attention(qq, kk, vv, axis_name, causal, scale)
        raise ValueError(f"unknown sp impl {impl!r}")

    return shard_map(
        local, mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec,
        check_vma=False,
    )(q, k, v)
