"""Partition rules: pytree path patterns → PartitionSpecs.

The declarative replacement for the reference's per-framework wrapper classes
(DDP/FSDP wrapping in ``train/torch/train_loop_utils.py:91-100``): a model
ships a list of ``(path_regex, spec)`` rules; applying them to a params
pytree yields NamedShardings for ``jax.jit`` in/out shardings. XLA then emits
the all-gathers/reduce-scatters that DDP/FSDP would do by hand.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered (regex, PartitionSpec) rules; first match wins."""

    def __init__(self, rules: Sequence[Tuple[str, P]], default: P = P()):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._default = default

    def spec_for(self, path_string: str) -> P:
        for pat, spec in self._rules:
            if pat.search(path_string):
                return spec
        return self._default

    def tree_specs(self, tree: Any):
        """A pytree of PartitionSpecs matching ``tree``'s structure."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(_path_str(path)), tree)

    def tree_shardings(self, tree: Any, mesh: Mesh):
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(mesh, self.spec_for(_path_str(path))),
            tree)


def named_sharding(mesh: Mesh, *axes) -> NamedSharding:
    return NamedSharding(mesh, P(*axes))


def shard_pytree(tree: Any, mesh: Mesh, rules: ShardingRules):
    """Device-put a pytree according to the rules (used at init/restore)."""
    shardings = rules.tree_shardings(tree, mesh)
    return jax.tree.map(jax.device_put, tree, shardings)


# Spec fragments shared by transformer models. Conventions:
#   batch axis   -> ("dp", "fsdp")      [+ "sp" shards sequence]
#   param matrices -> ("fsdp" on one dim, "tp" on the other)
BATCH_AXES = ("dp", "fsdp")


def data_spec(extra_seq_axis: Optional[str] = None) -> P:
    """[batch, seq, ...] inputs: batch over data axes, seq over sp if used."""
    return P(BATCH_AXES, extra_seq_axis)
